"""Device-side CAVLC entropy for P-frames.

Companion to :mod:`.cavlc_device` (the intra entropy stage): the same
slot -> block -> MB -> row bitmerge hierarchy, with the P-slice MB layer
built on device instead of a fixed syntax table:

- **mb_skip_run**: with slice-per-row, a skipped MB is exactly
  ``mv == (0,0) and cbp == 0``; each coded MB's preceding run is a
  row-local cummax over coded positions, and a per-row trailing-run slot
  covers slices that end in skips — all dense ops, no sequencing.
- **mvd**: mvp is the left MB's MV (spec §8.4.1.3 with B/C in other
  slices), so mvd is one shift + subtract over the MV field; signed
  Exp-Golomb lengths come from a bit-length gather table.
- **residual blocks**: 26 per MB (16 luma 16-coef blocks — inter MBs have
  no luma DC Hadamard — 2 chroma DC, 8 chroma AC), gated by the inter
  CBP (per-8x8-group luma bits, Table 9-4 inter codeNum mapping).

The host pulls the same flat metadata+bitstream buffer as the intra path
(one bucketed transfer per frame, ~100x smaller than the level tensors the
host-entropy P path pulls), and the reconstruction planes never leave the
device — they are the next frame's reference.

Byte-identity contract with the Python reference
(:func:`..bitstream.h264_entropy.encode_p_picture`) is enforced in tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..bitstream.h264_entropy import _CBP_INTER_BY_CODENUM
from . import bitmerge
from .cavlc_device import (FLAT_CAP_WORDS, MAX_META_ROWS, META_WORDS,
                           code_blocks, nc_grid)
from .h264_inter import RING_DONATE

_I32 = np.int32

P_MB_BLOCKS = 26          # 16 luma + 2 chroma DC + 8 chroma AC
P_MB_BLOCKS_I = 27        # + Intra16x16DCLevel (tune=hq I16-in-P path)
HDR_SLOT_COUNT = 7        # skip_run, mb_type, mvd_x, mvd_y, cbp,
                          # intra_chroma_pred_mode, qp_delta (per-slot
                          # zero lengths collapse: an inter MB emits no
                          # chroma-mode bits, an intra MB no mvd/cbp)

# bit_length(v) for v in [0, 2048): the largest ue argument is a fully
# skipped row's trailing run (code = row_width_in_MBs + 1, so 2048 covers
# widths beyond 32K px) plus every mvd/cbp codeword.
_BITLEN = np.zeros(2048, _I32)
for _v in range(1, 2048):
    _BITLEN[_v] = _v.bit_length()

# cbp value (0..47) -> inter codeNum (Table 9-4)
_CBP_TO_CODENUM = np.zeros(48, _I32)
for _cn, _cbp in enumerate(_CBP_INTER_BY_CODENUM):
    _CBP_TO_CODENUM[_cbp] = _cn
del _cn, _cbp, _v


def _ue(v):
    """Unsigned Exp-Golomb as (value, length) slot arrays (v < 2047)."""
    code = v + 1
    n = jnp.asarray(_BITLEN)[code]
    return code.astype(jnp.uint32), 2 * n - 1


def _se(v):
    """Signed Exp-Golomb as (value, length)."""
    code = jnp.where(v > 0, 2 * v - 1, -2 * v)
    return _ue(code)


def p_mb_header_slots(mv, cbp, qp_se=None, mb_intra=None):
    """Per-MB P-slice header slots + per-row trailing skip run.

    mv: (R, C, 2) quarter-pel; cbp: (R, C) coded_block_pattern.
    Returns (vals (R,C,7) uint32, lens (R,C,7) int32 — all-zero lens for
    skipped MBs, trail_vals (R,) uint32, trail_lens (R,)).

    ``qp_se`` (tune=hq): per-MB (value, length) override for the
    mb_qp_delta slot, lengths pre-gated to the MBs whose syntax carries
    it (cbp != 0, or I_16x16 which always codes it).

    ``mb_intra`` (tune=hq I16-in-P): (R, C) bool — MBs coded I_16x16/DC
    inside the P slice.  For those, ``cbp`` carries the INTRA pattern
    (luma 0/15 + 16 * chroma): mb_type = 5 + (1 + 2 + 4 * cbp_chroma +
    12 * [cbp_luma != 0]) per Table 7-11 with predMode DC, the mvd and
    coded_block_pattern slots are absent (I16 cbp rides in mb_type), and
    intra_chroma_pred_mode DC is one ue(0) bit.  An intra MB is never
    skipped, and its (0, 0) entry in ``mv`` is exactly the zero vector
    the spec substitutes for an intra neighbor in mv prediction, so the
    plain left-shift mvp below stays normative.
    """
    nr, nc = cbp.shape
    intra = (jnp.zeros((nr, nc), bool) if mb_intra is None
             else jnp.asarray(mb_intra, bool))
    zero_mv = jnp.all(mv == 0, axis=-1)
    skip = zero_mv & (cbp == 0) & ~intra
    coded = ~skip

    idx = jnp.arange(nc, dtype=jnp.int32)[None, :]
    # index of the most recent coded MB at or before each position
    coded_idx = jnp.where(coded, idx, -1)
    prev_inclusive = jax.lax.cummax(coded_idx, axis=1)
    # previous coded STRICTLY before: shift right with -1 fill
    prev_excl = jnp.concatenate(
        [jnp.full((nr, 1), -1, jnp.int32), prev_inclusive[:, :-1]], axis=1)
    run = idx - prev_excl - 1                          # (R, C)

    # mvp = left MB's mv (skipped MBs carry (0,0) which is their derived
    # motion, so a plain shift is exact); first column predicts from 0.
    mvp = jnp.concatenate(
        [jnp.zeros((nr, 1, 2), mv.dtype), mv[:, :-1]], axis=1)
    mvd = (mv - mvp).astype(jnp.int32)

    v_run, l_run = _ue(run)
    # mb_type: P_L0_16x16 = ue(0); I_16x16 in a P slice = ue(5 + intra
    # table index), predMode DC (2) with the I16 cbp folded in
    t_intra = 8 + 4 * (cbp >> 4) + jnp.where((cbp & 15) > 0, 12, 0)
    v_type, l_type = _ue(jnp.where(intra, t_intra, 0))
    v_mx, l_mx = _se(mvd[..., 1])                      # quarter-pel x
    v_my, l_my = _se(mvd[..., 0])                      # quarter-pel y
    v_cbp, l_cbp = _ue(jnp.asarray(_CBP_TO_CODENUM)[
        jnp.where(intra, 0, cbp)])
    not_i = ~intra
    l_mx = l_mx * not_i
    l_my = l_my * not_i
    l_cbp = l_cbp * not_i
    # intra_chroma_pred_mode: DC = ue(0), intra MBs only
    v_icp = jnp.ones_like(run, jnp.uint32)
    l_icp = jnp.where(intra, 1, 0)
    if qp_se is None:
        v_qpd, l_qpd = _se(jnp.zeros_like(run))
        # qp_delta iff cbp != 0, or always for I_16x16
        l_qpd = jnp.where((cbp > 0) | intra, l_qpd, 0)
    else:
        v_qpd, l_qpd = qp_se                           # tune=hq chain

    vals = jnp.stack([v_run, v_type, v_mx, v_my, v_cbp, v_icp, v_qpd],
                     axis=-1)
    lens = jnp.stack([l_run, l_type, l_mx, l_my, l_cbp, l_icp, l_qpd],
                     axis=-1)
    lens = lens * coded[:, :, None]                    # skip MBs emit nothing

    # trailing skip run: MBs after the last coded one (possibly the whole
    # row); length 0 when the row ends on a coded MB.
    last_coded = prev_inclusive[:, -1]                 # (R,)
    trail = nc - 1 - last_coded
    tv, tl = _ue(trail)
    trail_lens = jnp.where(trail > 0, tl, 0)
    return vals, lens, tv, trail_lens, skip


def p_frame_block_slots(out: dict):
    """Inter residual tensors (ops/h264_inter.encode_p_frame) -> block
    slots + gates.  Returns (values, lengths, cbp, mv) with values/lengths
    (R, C, 26, 34) — or (R, C, 27, 34) when the tune=hq I16-in-P path is
    active (``mb_intra`` in ``out``): block 0 is then Intra16x16DCLevel
    (gated to intra MBs; always coded there) and the 16 luma slots carry
    15-coefficient AC blocks for intra MBs (max_coeff 15 — total_zeros is
    absent when total_coeff reaches it) while inter MBs keep their
    16-coefficient LumaLevel4x4 blocks.  ``cbp`` for an intra MB is the
    INTRA pattern (0/15 luma + 16 * chroma) the mb_type table folds in."""
    mb_intra = out.get("mb_intra")
    mv = out["mv"].astype(jnp.int32)
    luma = out["luma"].astype(jnp.int32)               # (R, C, 16, 16)
    cb_dc = out["cb_dc"].astype(jnp.int32)
    cb_ac = out["cb_ac"].astype(jnp.int32)
    cr_dc = out["cr_dc"].astype(jnp.int32)
    cr_ac = out["cr_ac"].astype(jnp.int32)
    nr, nc_mb = luma.shape[:2]

    # --- inter CBP: luma bit per 8x8 group, chroma 2 levels -------------
    luma_grp_any = jnp.any(
        luma.reshape(nr, nc_mb, 4, 4, 16) != 0, axis=(3, 4))   # (R,C,4)
    cbp_luma = (luma_grp_any
                * (1 << jnp.arange(4, dtype=jnp.int32))).sum(axis=2)
    chroma_ac_any = (jnp.any(cb_ac != 0, axis=(2, 3))
                     | jnp.any(cr_ac != 0, axis=(2, 3)))
    chroma_dc_any = (jnp.any(cb_dc != 0, axis=2)
                     | jnp.any(cr_dc != 0, axis=2))
    cbp_chroma = jnp.where(chroma_ac_any, 2,
                           jnp.where(chroma_dc_any, 1, 0))
    cbp = cbp_luma + 16 * cbp_chroma                   # (R, C)

    # --- per-4x4 total_coeff (gated by the group bit), nC grids ---------
    from .cavlc_device import _BLK_X, _BLK_Y

    grp_gate = luma_grp_any[:, :, jnp.arange(16) // 4]         # (R,C,16)
    tc_blk = jnp.count_nonzero(luma, axis=3).astype(jnp.int32) * grp_gate
    if mb_intra is not None:
        intra = jnp.asarray(mb_intra, bool)
        i16_dc = out["i16_dc"].astype(jnp.int32)       # (R, C, 16)
        i16_ac = out["i16_ac"].astype(jnp.int32)       # (R, C, 16, 15)
        cl15 = jnp.any(i16_ac != 0, axis=(2, 3))       # (R, C)
        # the header's cbp: intra pattern for intra MBs (device zeroes
        # the inter luma there, so cbp_luma is already 0)
        cbp = jnp.where(intra, jnp.where(cl15, 15, 0) + 16 * cbp_chroma,
                        cbp)
        # neighbor total_coeff contexts: an intra MB's 4x4 counts come
        # from its (gated) AC block
        tc_i = (jnp.count_nonzero(i16_ac, axis=3).astype(jnp.int32)
                * cl15[:, :, None])
        tc_blk = jnp.where(intra[:, :, None], tc_i, tc_blk)
    tc_luma = jnp.zeros((nr, nc_mb, 4, 4), jnp.int32)
    tc_luma = tc_luma.at[:, :, jnp.asarray(_BLK_Y),
                         jnp.asarray(_BLK_X)].set(tc_blk)

    def chroma_tc(ac):
        t = jnp.count_nonzero(ac, axis=3).astype(jnp.int32)
        t = t * (cbp_chroma == 2)[:, :, None]
        return t.reshape(nr, nc_mb, 2, 2)

    tc_cb, tc_cr = chroma_tc(cb_ac), chroma_tc(cr_ac)
    ncl = nc_grid(tc_luma, tc_luma[:, :, :, 3])
    nccb = nc_grid(tc_cb, tc_cb[:, :, :, 1])
    nccr = nc_grid(tc_cr, tc_cr[:, :, :, 1])

    nmb = nr * nc_mb
    nblk = P_MB_BLOCKS if mb_intra is None else P_MB_BLOCKS_I

    def pad16(a):
        k = a.shape[-1]
        return jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, 16 - k)])

    luma_eff = luma
    if mb_intra is not None:
        luma_eff = jnp.where(intra[:, :, None, None],
                             pad16(i16_ac), luma)
    parts = [
        luma_eff,                                      # 16 luma blocks
        pad16(cb_dc)[:, :, None, :],
        pad16(cr_dc)[:, :, None, :],
        pad16(cb_ac),
        pad16(cr_ac)]
    if mb_intra is not None:
        parts.insert(0, i16_dc[:, :, None, :])         # Intra16x16DCLevel
    blk_levels = jnp.concatenate(parts, axis=2)        # (R, C, nblk, 16)

    nc_luma_blk = ncl[:, :, jnp.asarray(_BLK_Y), jnp.asarray(_BLK_X)]
    nc_c = lambda g: g.reshape(nr, nc_mb, 4)
    nc_parts = [
        nc_luma_blk,
        jnp.zeros((nr, nc_mb, 2), jnp.int32),          # chroma DC: nC=-1
        nc_c(nccb), nc_c(nccr)]
    if mb_intra is not None:
        # Intra16x16DCLevel derives nC exactly as luma4x4BlkIdx 0
        nc_parts.insert(0, ncl[:, :, 0, 0][:, :, None])
    blk_nc = jnp.concatenate(nc_parts, axis=2)         # (R, C, nblk)

    off = 0 if mb_intra is None else 1
    is_cdc = np.zeros(nblk, bool)
    is_cdc[off + 16] = is_cdc[off + 17] = True
    max_coeff = np.full(nblk, 15, _I32)
    max_coeff[off:off + 16] = 16
    max_coeff[off + 16] = max_coeff[off + 17] = 4
    if mb_intra is None:
        mc = jnp.asarray(np.tile(max_coeff, nmb))
    else:
        max_coeff[0] = 16                              # Intra16x16DCLevel
        mc = jnp.broadcast_to(jnp.asarray(max_coeff),
                              (nr, nc_mb, nblk))
        # intra luma AC blocks are 15-coefficient (total_zeros absent
        # when total_coeff == 15, unlike the 16-coef inter blocks)
        mc = jnp.where(intra[:, :, None]
                       & (jnp.arange(nblk) >= off)[None, None, :]
                       & (jnp.arange(nblk) < off + 16)[None, None, :],
                       15, mc)
        mc = mc.reshape(-1)

    values, lengths = code_blocks(
        blk_levels.reshape(nmb * nblk, 16),
        blk_nc.reshape(-1),
        jnp.asarray(np.tile(is_cdc, nmb)),
        mc)
    values = values.reshape(nr, nc_mb, nblk, -1)
    lengths = lengths.reshape(nr, nc_mb, nblk, -1)

    gate = jnp.ones((nr, nc_mb, nblk), bool)
    if mb_intra is None:
        gate = gate.at[:, :, 0:16].set(grp_gate)
    else:
        gate = gate.at[:, :, 0].set(intra)             # DC: intra only
        gate = gate.at[:, :, 1:17].set(
            jnp.where(intra[:, :, None], cl15[:, :, None], grp_gate))
    gate = gate.at[:, :, off + 16:off + 18].set(
        (cbp_chroma > 0)[:, :, None])
    gate = gate.at[:, :, off + 18:off + 26].set(
        (cbp_chroma == 2)[:, :, None])
    lengths = lengths * gate[:, :, :, None]
    return values, lengths, cbp, mv


def pack_p_frame(values, lengths, hdr6_vals, hdr6_lens, trail_vals,
                 trail_lens, slice_vals, slice_lens, qp_sum=None):
    """Pack a P frame's slots into the flat metadata+bitstream buffer
    (same layout as cavlc_device.pack_frame; ``qp_sum`` rides in
    META_QP_SUM_WORD under tune=hq)."""
    nr, nc_mb = values.shape[:2]

    blk_words, blk_bits, blk_ovf = bitmerge.slots_to_words(
        values, lengths, bitmerge.BLOCK_WORDS)         # (R,C,26,8)

    # MB header piece (skip_run..qp_delta; <= ~40 bits -> block buffer)
    hw, hb, h_ovf = bitmerge.slots_to_words(
        hdr6_vals, hdr6_lens, bitmerge.BLOCK_WORDS)    # (R, C, 8)

    pieces = jnp.concatenate([hw[:, :, None, :], blk_words], axis=2)
    piece_bits = jnp.concatenate([hb[:, :, None], blk_bits], axis=2)
    mb_words, mb_bits, mb_ovf = bitmerge.merge_pieces_dense(
        pieces, piece_bits, bitmerge.MB_WORDS)         # (R, C, 64)

    hdr_words4, hdr_bits, _ = bitmerge.slots_to_words(
        slice_vals, slice_lens, 4)                     # (R, 4)
    hdr_words = jnp.pad(hdr_words4, ((0, 0), (0, bitmerge.MB_WORDS - 4)))

    # trailing skip run piece (<= 23 bits); the shift is guarded because a
    # zero-length piece would shift by 32 (undefined across backends).
    trailrun_words = jnp.zeros((nr, bitmerge.MB_WORDS), jnp.uint32)
    trailrun_words = trailrun_words.at[:, 0].set(jnp.where(
        trail_lens > 0,
        trail_vals.astype(jnp.uint32)
        << (32 - jnp.maximum(trail_lens, 1)).astype(jnp.uint32),
        jnp.uint32(0)))

    body_bits = hdr_bits + mb_bits.sum(axis=1) + trail_lens
    pad = (8 - ((body_bits + 1) % 8)) % 8
    trail_words = jnp.zeros((nr, bitmerge.MB_WORDS), jnp.uint32)
    trail_words = trail_words.at[:, 0].set(jnp.uint32(1) << 31)
    trail_bits = pad + 1

    n_pieces = 1 + nc_mb + 2                           # hdr, MBs, run, rbsp
    p2 = 1 << int(np.ceil(np.log2(n_pieces)))
    row_pieces = jnp.concatenate([
        hdr_words[:, None, :], mb_words,
        trailrun_words[:, None, :], trail_words[:, None, :],
        jnp.zeros((nr, p2 - n_pieces, bitmerge.MB_WORDS), jnp.uint32)],
        axis=1)
    row_bits_in = jnp.concatenate([
        hdr_bits[:, None], mb_bits, trail_lens[:, None],
        trail_bits[:, None], jnp.zeros((nr, p2 - n_pieces), jnp.int32)],
        axis=1)
    row_words_buf, row_bits = bitmerge.merge_pieces_tree(
        row_pieces, row_bits_in)

    row_bytes = row_bits // 8
    row_words = (row_bytes + 3) // 4
    word_off = jnp.cumsum(row_words) - row_words
    total_words = word_off[-1] + row_words[-1]

    word_cum = jnp.cumsum(row_words)
    j = jnp.arange(FLAT_CAP_WORDS, dtype=jnp.int32)
    r = (j[:, None] >= word_cum[None, :]).sum(axis=1)
    rc = jnp.clip(r, 0, nr - 1)
    src = rc * row_words_buf.shape[1] + (j - word_off[rc])
    src = jnp.clip(src, 0, nr * row_words_buf.shape[1] - 1)
    flat_words = jnp.where(j < total_words,
                           row_words_buf.reshape(-1)[src], 0)

    overflow = (jnp.any(blk_ovf) | jnp.any(h_ovf) | jnp.any(mb_ovf)
                | (total_words > FLAT_CAP_WORDS))

    meta = jnp.zeros(META_WORDS, jnp.uint32)
    meta = meta.at[0].set(overflow.astype(jnp.uint32))
    meta = meta.at[1].set(total_words.astype(jnp.uint32))
    meta = meta.at[2:2 + nr].set(row_bytes.astype(jnp.uint32))
    meta = meta.at[2 + MAX_META_ROWS:2 + MAX_META_ROWS + nr].set(
        word_off.astype(jnp.uint32))
    if qp_sum is not None:
        from .cavlc_device import META_QP_SUM_WORD
        meta = meta.at[META_QP_SUM_WORD].set(qp_sum.astype(jnp.uint32))

    allw = jnp.concatenate([meta, flat_words])
    flat = jnp.stack([(allw >> 24) & 0xFF, (allw >> 16) & 0xFF,
                      (allw >> 8) & 0xFF, allw & 0xFF],
                     axis=-1).reshape(-1).astype(jnp.uint8)
    return flat, overflow


@functools.partial(jax.jit, static_argnames=("qp", "tune", "p_intra"),
                   donate_argnames=RING_DONATE)
def encode_p_cavlc_frame(y, cb, cr, ref_y, ref_cb, ref_cr,
                         hdr_vals, hdr_lens, qp: int, tune: str = "off",
                         next_y=None, p_intra: bool = False):
    """Fused P-frame device stage: ME/MC/residual (ops/h264_inter) +
    device CAVLC.  Returns (flat, recon_y, recon_cb, recon_cr, mv, nnz,
    levels) — only ``flat``'s prefix crosses the host link; the recon
    stays on device as the next reference, written IN PLACE of the
    donated refs (recon shapes/dtypes match exactly, so XLA aliases the
    buffers — the ring-buffer contract of ROADMAP item 2; callers must
    treat the passed refs as consumed).  ``levels`` carries the residual
    tensors the host entropy coder would need, so a flat-cap overflow
    falls back to host CAVLC of the SAME levels without ever re-reading
    the (now dead) reference planes — the levels are lazy device arrays
    and cross the link only on that rare path."""
    from . import h264_inter

    out = h264_inter.encode_p_frame.__wrapped__(
        y, cb, cr, ref_y, ref_cb, ref_cr, qp, "alt", tune, next_y,
        p_intra)
    return _finish_p(out, hdr_vals, hdr_lens, slice_qp=qp)


def encode_p_cavlc_frame_padded(y, cb, cr, ref_y_pad, ref_cb_pad,
                                ref_cr_pad, hdr_vals, hdr_lens, qp: int,
                                tune: str = "off", next_y=None,
                                p_intra: bool = False):
    """P stage from ``_PAD``-padded references — the spatially-sharded
    batch path's entry, where the padding rows are neighbor-shard halos
    instead of edge replication (parallel/batch.py).  Same 7-tuple
    return as :func:`encode_p_cavlc_frame` (shard callers drop the
    trailing ``levels`` before the collective gathers)."""
    from . import h264_inter

    out = h264_inter.encode_p_frame_padded_ref(
        y, cb, cr, ref_y_pad, ref_cb_pad, ref_cr_pad, qp, tune=tune,
        next_y=next_y, p_intra=p_intra)
    return _finish_p(out, hdr_vals, hdr_lens, slice_qp=qp)


def _finish_p(out: dict, hdr_vals, hdr_lens, slice_qp: int = None):
    import jax.numpy as jnp

    values, lengths, cbp, mv = p_frame_block_slots(out)
    mb_intra = out.get("mb_intra")
    qp_se = None
    qp_sum = None
    if "qp_map" in out:
        from . import aq
        codes = cbp > 0            # skip MBs have cbp == 0 too
        if mb_intra is not None:   # I_16x16 always codes mb_qp_delta
            codes = codes | jnp.asarray(mb_intra, bool)
        eff, delta = aq.qp_chain(out["qp_map"], codes, int(slice_qp))
        from .cavlc_device import se_slots
        sv, sl = se_slots(delta)
        qp_se = (sv, jnp.where(codes, sl, 0))
        qp_sum = jnp.sum(eff).astype(jnp.uint32)
    hv6, hl6, tv, tl, _skip = p_mb_header_slots(mv, cbp, qp_se=qp_se,
                                                mb_intra=mb_intra)
    flat, _ = pack_p_frame(values, lengths, hv6, hl6, tv, tl,
                           hdr_vals, hdr_lens, qp_sum=qp_sum)
    # per-4x4 coded-coefficient flags in raster [by][bx] order — the
    # deblocking bS=2 input (ops/h264_deblock.p_bs)
    luma = out["luma"]                                  # (R,C,16blk,16)
    nnz_idx = jnp.any(luma != 0, axis=-1)               # blkIdx order
    nr, nc = nnz_idx.shape[:2]
    from .h264_device import LUMA_BLOCK_ORDER
    import numpy as np
    nnz = jnp.zeros((nr, nc, 4, 4), bool)
    nnz = nnz.at[:, :, np.asarray(LUMA_BLOCK_ORDER[:, 1]),
                 np.asarray(LUMA_BLOCK_ORDER[:, 0])].set(nnz_idx)
    # residual levels for the host-entropy overflow fallback (mv rides
    # separately); pulled only when the flat cap overflowed.  The
    # tune=hq qp plane rides along: the fallback must re-emit the SAME
    # per-MB deltas the levels were quantized under.
    levels = {k: out[k] for k in ("luma", "cb_dc", "cb_ac",
                                  "cr_dc", "cr_ac")}
    if "qp_map" in out:
        levels["qp_map"] = out["qp_map"]
    if mb_intra is not None:       # I16-in-P tensors for the same fallback
        for k in ("mb_intra", "i16_dc", "i16_ac"):
            levels[k] = out[k]
    return (flat, out["recon_y"], out["recon_cb"], out["recon_cr"],
            out["mv"], nnz, levels)
