"""Device-resident encode-throughput loops (the compute-only benchmark).

The serving benchmark measures the whole pipeline — host color conversion,
host->device transfer, device encode, bitstream pull.  On a tunnel-attached
chip the link dominates and hides what the device itself can sustain (the
reference's NVENC envelope is opaque silicon; ours is measurable).  These
loops answer the device-only question honestly:

- K encode steps run inside ONE ``lax.fori_loop`` with the trip count as a
  *traced* scalar (one compile, any K) and a data dependency per iteration
  (input planes perturbed by the loop index; P frames chain their recon as
  the next reference) so XLA cannot hoist or elide iterations.
- Only a 4-byte checksum leaves the device.  Wall-clock of a K-step call is
  ``RTT + K * step_ms``; differencing two trip counts cancels the RTT and
  every other fixed cost, leaving pure device throughput.

SURVEY.md §6: the 1080p60 real-time bar is 16.7 ms/frame — `step_ms` is the
number that says whether the codec kernels themselves clear it.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from jax import lax


def _perturb(plane, i):
    """Mix the loop index into every pixel (cheap elementwise add) so the
    whole frame's encode chain depends on ``i`` — defeats loop-invariant
    code motion without changing the workload's character."""
    return (plane.astype(jnp.int32) + (i & 1)).clip(0, 255).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("qp", "i16_modes"))
def intra_loop(y, cb, cr, hv, hl, steps, qp: int, i16_modes: str = "auto"):
    """``steps`` intra CAVLC frame encodes, device-resident; returns a
    uint32 checksum (forces execution, 4-byte pull)."""
    from . import cavlc_device

    def body(i, acc):
        flat = cavlc_device.encode_intra_cavlc_frame_yuv(
            _perturb(y, i), _perturb(cb, i), _perturb(cr, i),
            hv, hl, qp, with_recon=False, i16_modes=i16_modes)
        return acc + flat[cavlc_device.META_WORDS * 4].astype(jnp.uint32)

    return lax.fori_loop(0, steps, body, jnp.uint32(0))


@functools.partial(jax.jit, static_argnames=("qp", "deblock"))
# NOT donated on purpose: measure_steady_state calls the loop at two
# trip counts with the SAME ref buffers (the differencing trick), so
# donating them would invalidate the caller's arrays between timed calls.
# dngd: ignore[jax-donate-missing]
def p_loop(y, cb, cr, ref_y, ref_cb, ref_cr, hv, hl, steps, qp: int,
           deblock: bool = True):
    """``steps`` P-frame encodes chained through their reconstruction (the
    real GOP dependency: frame N+1 references frame N's recon).  With
    ``deblock`` (the serving default, models/h264.py `_submit_p_device`)
    each recon passes through the in-loop filter before becoming the next
    reference, so step_ms matches what serving actually sustains."""
    from . import cavlc_device, cavlc_p_device, h264_deblock

    def body(i, carry):
        acc, ry, rcb, rcr = carry
        flat, ry2, rcb2, rcr2, mv, nnz, _lv = \
            cavlc_p_device.encode_p_cavlc_frame(
                _perturb(y, i), _perturb(cb, i), _perturb(cr, i),
                ry, rcb, rcr, hv, hl, qp)
        if deblock:
            ry2, rcb2, rcr2 = h264_deblock.deblock_frame(
                ry2, rcb2, rcr2, qp, nnz_blk=nnz, mv=mv)
        acc = acc + flat[cavlc_device.META_WORDS * 4].astype(jnp.uint32)
        return acc, ry2, rcb2, rcr2

    out = lax.fori_loop(0, steps, body,
                        (jnp.uint32(0), ref_y, ref_cb, ref_cr))
    return out[0]


@functools.partial(jax.jit,
                   static_argnames=("qp", "i16_modes", "binarize"))
def cabac_intra_loop(y, cb, cr, steps, qp: int, i16_modes: str = "auto",
                     binarize: bool = False):
    """``steps`` CABAC-path device stages (intra transform+quant +
    compaction — everything that runs on device per frame when
    ``ENCODER_ENTROPY=cabac``; the host stage overlaps in the serving
    pipeline).  ``binarize=True`` measures the round-6 split (device
    binarization + ctxIdx via ops/cabac_binarize — the host then runs
    only the arithmetic engine); False keeps the round-5 level_pack
    transport for the old/new comparison."""
    from . import cabac_binarize, h264_device, level_pack

    def body(i, acc):
        lv = h264_device.encode_intra_frame_yuv(
            _perturb(y, i), _perturb(cb, i), _perturb(cr, i), qp,
            i16_modes=i16_modes)
        if binarize:
            buf = cabac_binarize.binarize_intra(
                lv["luma_dc"], lv["luma_ac"], lv["cb_dc"], lv["cb_ac"],
                lv["cr_dc"], lv["cr_ac"], lv["pred_mode"], lv["mb_i4"],
                lv["i4_modes"], lv["luma_i4"])
        else:
            buf = level_pack.pack_levels(lv, level_pack.INTRA_KEYS)
        return acc + buf[2].astype(jnp.uint32)

    return lax.fori_loop(0, steps, body, jnp.uint32(0))


@functools.partial(jax.jit, static_argnames=("qp", "refine"))
# not donated on purpose — see p_loop.
# dngd: ignore[jax-donate-missing]
def inter_loop(y, cb, cr, ref_y, ref_cb, ref_cr, steps, qp: int,
               refine: str = "alt"):
    """``steps`` inter stages (ME/MC/residual, NO deblock or entropy),
    recon-chained — isolates the ME-dominated stage so the round-6
    alternate-line refinement ("alt") can be profiled against the
    round-5 full-line re-rank ("full")."""
    from . import h264_inter

    def body(i, carry):
        acc, ry, rcb, rcr = carry
        out = h264_inter.encode_p_frame(
            _perturb(y, i), _perturb(cb, i), _perturb(cr, i),
            ry, rcb, rcr, qp=qp, refine=refine)
        acc = acc + out["luma"][0, 0, 0, 0].astype(jnp.uint32)
        return acc, out["recon_y"], out["recon_cb"], out["recon_cr"]

    out = lax.fori_loop(0, steps, body,
                        (jnp.uint32(0), ref_y, ref_cb, ref_cr))
    return out[0]


@functools.partial(jax.jit, static_argnames=("qp", "group"))
def deblock_loop(y, cb, cr, steps, qp: int, group: int = 0):
    """``steps`` loop-filter applications chained through their output
    (intra bS pattern) — isolates the deblock stage so the round-6
    wavefront grouping (group=0 auto) can be profiled against the
    round-5 per-column scan (group=1)."""
    from . import h264_deblock

    def body(i, carry):
        acc, fy, fcb, fcr = carry
        fy, fcb, fcr = h264_deblock.deblock_frame(
            _perturb(fy, i), _perturb(fcb, i), _perturb(fcr, i), qp,
            _group=group)
        return acc + fy[0, 0].astype(jnp.uint32), fy, fcb, fcr

    out = lax.fori_loop(0, steps, body, (jnp.uint32(0), y, cb, cr))
    return out[0]


@functools.partial(jax.jit, static_argnames=("qp", "deblock", "binarize"))
# not donated on purpose — see p_loop.
# dngd: ignore[jax-donate-missing]
def cabac_p_loop(y, cb, cr, ref_y, ref_cb, ref_cr, steps, qp: int,
                 deblock: bool = True, binarize: bool = False):
    """``steps`` CABAC-path P device stages (inter predict + transform +
    quant + deblock + compaction), recon-chained like :func:`p_loop`.
    ``binarize=True`` measures the round-6 device-binarization split."""
    from . import cabac_binarize, h264_deblock, h264_inter, level_pack
    from .h264_device import nnz_blocks_raster

    def body(i, carry):
        acc, ry, rcb, rcr = carry
        out = h264_inter.encode_p_frame(
            _perturb(y, i), _perturb(cb, i), _perturb(cr, i),
            ry, rcb, rcr, qp=qp)
        ry2, rcb2, rcr2 = (out["recon_y"], out["recon_cb"],
                           out["recon_cr"])
        if deblock:
            ry2, rcb2, rcr2 = h264_deblock.deblock_frame(
                ry2, rcb2, rcr2, qp, nnz_blk=nnz_blocks_raster(out["luma"]),
                mv=out["mv"].astype(jnp.int32))
        if binarize:
            buf = cabac_binarize.binarize_p(
                out["mv"], out["luma"], out["cb_dc"], out["cb_ac"],
                out["cr_dc"], out["cr_ac"])
        else:
            buf = level_pack.pack_levels(out, level_pack.P_KEYS)
        acc = acc + buf[2].astype(jnp.uint32)
        return acc, ry2, rcb2, rcr2

    out = lax.fori_loop(0, steps, body,
                        (jnp.uint32(0), ref_y, ref_cb, ref_cr))
    return out[0]


# ---------------------------------------------------------------------------
# Persistent compiled serving graph: the GOP-chunk SUPER-STEP
#
# The per-frame serving loop crosses Python once per frame (submit p50
# 14-15 ms on the r05 tunnel ledger — link-dominated but dispatch-heavy),
# which caps pipelined throughput far below what the device sustains
# intra.  The super-step moves the whole P-run loop INTO XLA: one jitted
# call encodes a GOP-chunk of K frames via ``lax.scan``, chaining the
# reconstruction (and in-loop deblock) through the scan carry exactly as
# the per-frame path chains it through ``self._ref`` — so the emitted
# bitstream is byte-identical (tested GOP-deep), while the host pays ONE
# dispatch per chunk instead of K.
#
# Ring-buffer donation: the reference planes are ``donate_argnames``'d
# and the new reference is returned in the same position/shape/dtype, so
# XLA aliases the buffers — iteration N+1's ref ring IS iteration N's
# output ring, never a copy, and matching in/out layout means chained
# chunk calls never repartition (the pjit contract SNIPPETS.md [1]/[3]
# prescribes: out specs of call N == in specs of call N+1).  The frame
# ring (ys/cbs/crs) is deliberately NOT donated: no output shares its
# shape, so donation could never alias it and would only emit
# "unusable donation" warnings; XLA frees it after the scan regardless.
#
# ``prefix_len`` bakes the host's pull-guess bucket into the program so
# the chunk's bitstream prefix is an OUTPUT of the same dispatch — the
# steady-state submit path is exactly one Python crossing per chunk
# (guess changes are bucketed decaying-max, so a re-bucket costs one
# recompile, which the retrace tripwire test pins).
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def build_p_chunk_step(qp: int, deblock: bool = True,
                       entropy: str = "cavlc", ingest: str = "yuv",
                       prefix_len: int = 0, spatial_shards: int = 1,
                       tune: str = "off", p_intra: bool = False,
                       damage_bucket: int = 0):
    """Build the jitted GOP-chunk super-step for one (qp, deblock,
    entropy, ingest, prefix_len, spatial_shards) configuration.

    ``spatial_shards > 1`` grows the program a SPATIAL axis: the same
    K-frame donated-ring scan, but each frame's MB rows sharded across
    that many chips inside ``shard_map`` — halo exchange and sharded
    deblock inside the scan body, per-shard entropy gathered per frame
    (``parallel.batch.h264_spatial_chunk_step`` is the implementation;
    this builder is the single serving entry).  Same 7-tuple contract
    with ``flats``/``prefix`` carrying an extra shard axis
    ``(K, nx, L)``; the ref ring is donated and returned under one
    fixed ``P("spatial", None)`` spec so chained chunks never
    repartition.  Spatial mode requires ``ingest="yuv"`` (planes are
    staged pre-converted; splitting an RGB frame's 4:2:0 subsample
    across a shard seam would change rounding at the boundary).

    The returned callable specializes per input SHAPE (chunk size and
    geometry are carried by the arrays), so one builder result serves
    every chunk length and every geometry bucket with one compile each:

    - ``entropy="cavlc"``:   ``step(ys, cbs, crs, ref_y, ref_cb, ref_cr,
      hv, hl) -> (flats, prefix, ref_y', ref_cb', ref_cr', mvs,
      levels)`` where ``ys`` is ``(K, H, W)`` uint8 (``(K, h, w, 3)``
      RGB under ``ingest="rgb"``, fusing the capture-ingest YUV
      conversion into the same program), ``hv``/``hl`` are the K frames'
      slice-header slots stacked on axis 0, ``flats`` is ``(K, L)`` and
      ``prefix`` its first ``prefix_len`` bytes per frame (0 = whole
      buffer; the host prefetches only the prefix).
    - ``entropy="cabac"``:   same signature minus ``hv``/``hl`` —
      emits the device-binarized (bin, ctxIdx, bypass) record streams
      (ops/cabac_binarize); the host replays only the arithmetic engine.

    ``mvs``/``levels`` stay lazy on device and cross the link only on a
    flat-cap overflow (host-entropy fallback of the same levels).

    ``damage_bucket > 0`` builds the DAMAGE-MASKED chunk scan
    (ops/damage_mask): each staged frame carries a ``(damage_bucket,)``
    damaged-row worklist plus that worklist's gathered slice-header
    slots, and the scan body runs ``damage_mask.row_core`` — the same
    row-compacted core the per-frame masked step jits, so the two
    paths' bytes cannot drift.  The bucket is static (one compile per
    ladder rung); ``flats`` becomes ``(K, L_b)`` with each frame's meta
    describing ``damage_bucket`` rows; the ref ring is still donated,
    the recon rows scattered in place.  Signature gains a trailing
    ``rows`` argument: ``step(ys, cbs, crs, ref_y, ref_cb, ref_cr,
    hv_r, hl_r, rows)`` with ``hv_r``/``hl_r`` shaped
    ``(K, damage_bucket, S)`` and ``rows`` ``(K, damage_bucket)``.
    Masked chunks require cavlc entropy, yuv ingest, single shard.
    """
    from . import cabac_binarize, cavlc_p_device, h264_deblock, h264_inter
    from .h264_device import nnz_blocks_raster

    if entropy not in ("cavlc", "cabac"):
        raise ValueError(f"unknown chunk entropy {entropy!r}")
    if ingest not in ("yuv", "rgb"):
        raise ValueError(f"unknown chunk ingest {ingest!r}")
    if damage_bucket > 0 and (entropy != "cavlc" or ingest != "yuv"
                              or spatial_shards > 1):
        raise ValueError("masked chunk requires cavlc entropy, yuv "
                         "ingest and a single spatial shard")
    if tune == "hq" and entropy == "cabac":
        # the binarize record stream has no qp plumbing; models/h264
        # keeps hq CABAC on the dense host path (ring ineligible)
        raise ValueError("tune=hq chunk requires cavlc entropy")
    if p_intra and (entropy != "cavlc" or deblock):
        raise ValueError("p_intra requires cavlc entropy, deblock off")
    if spatial_shards > 1:
        if ingest != "yuv":
            raise ValueError("spatial chunk step requires yuv ingest")
        from ..parallel import batch
        mesh = batch.make_spatial_mesh(spatial_shards)
        return batch.h264_spatial_chunk_step(
            mesh, qp=qp, deblock=deblock, entropy=entropy,
            prefix_len=prefix_len, tune=tune, p_intra=p_intra)

    def ingest_frame(frame, pad_h: int, pad_w: int):
        if ingest == "yuv":
            return frame            # (y, cb, cr) tuple, already padded
        # fused capture-ingest: byte-identical to models.h264._yuv_stage
        from . import color
        h, w = frame.shape[0], frame.shape[1]
        rgb_p = jnp.pad(frame, ((0, pad_h - h), (0, pad_w - w), (0, 0)),
                        mode="edge")
        y, cb, cr = color.rgb_to_yuv420(rgb_p, matrix="video")
        q = lambda p: jnp.clip(jnp.round(p), 0, 255).astype(jnp.uint8)
        return q(y), q(cb), q(cr)

    def one_frame(frame, ry, rcb, rcr, hv_f, hl_f, next_y=None):
        pad_h, pad_w = ry.shape
        y, cb, cr = ingest_frame(frame, pad_h, pad_w)
        if entropy == "cavlc":
            flat, ny, ncb, ncr, mv, nnz, lv = \
                cavlc_p_device.encode_p_cavlc_frame.__wrapped__(
                    y, cb, cr, ry, rcb, rcr, hv_f, hl_f, qp, tune,
                    next_y, p_intra)
        else:
            out = h264_inter.encode_p_frame.__wrapped__(
                y, cb, cr, ry, rcb, rcr, qp, "alt", tune, next_y)
            ny, ncb, ncr = (out["recon_y"], out["recon_cb"],
                            out["recon_cr"])
            mv = out["mv"]
            nnz = nnz_blocks_raster(out["luma"])
            flat = cabac_binarize.binarize_p(
                out["mv"], out["luma"], out["cb_dc"], out["cb_ac"],
                out["cr_dc"], out["cr_ac"])
            lv = {k: out[k] for k in ("luma", "cb_dc", "cb_ac",
                                      "cr_dc", "cr_ac")}
        if deblock:
            ny, ncb, ncr = h264_deblock.deblock_frame.__wrapped__(
                ny, ncb, ncr, qp, nnz_blk=nnz, mv=mv.astype(jnp.int32))
        return flat, ny, ncb, ncr, mv, lv

    def scan_chunk(frames_xs, ref_y, ref_cb, ref_cr, hv, hl, rows=None):
        """frames_xs: (rgbs,) under rgb ingest, (ys, cbs, crs) under
        yuv.  Returns the 7-tuple the serving ring dequeues."""
        def body(carry, xs):
            ry, rcb, rcr = carry
            next_y = None
            if tune == "hq":
                *xs, next_y = xs
            if damage_bucket > 0:
                # masked scan body: the per-frame masked step's core
                # verbatim (row_core pads refs, gathers the worklist's
                # bands, deblocks in-program, scatters recon in place)
                from . import damage_mask
                y, cbf, crf, hv_f, hl_f, rows_f = xs
                flat, ny, ncb, ncr, mv, nnz, lv = damage_mask.row_core(
                    y, cbf, crf, ry, rcb, rcr, rows_f, hv_f, hl_f, qp,
                    tune=tune, next_y=next_y, p_intra=p_intra,
                    deblock=deblock)
                return (ny, ncb, ncr), (flat, mv, lv)
            if entropy == "cavlc":
                *frame_parts, hv_f, hl_f = xs
            else:
                frame_parts, hv_f, hl_f = xs, None, None
            frame = (frame_parts[0] if ingest == "rgb"
                     else tuple(frame_parts))
            if next_y is not None and ingest == "rgb":
                # lookahead needs the NEXT frame's luma: ingest it (the
                # hq axis trades device cycles for bits by design)
                next_y = ingest_frame(next_y, *ry.shape)[0]
            flat, ny, ncb, ncr, mv, lv = one_frame(
                frame, ry, rcb, rcr, hv_f, hl_f, next_y)
            return (ny, ncb, ncr), (flat, mv, lv)

        xs = tuple(frames_xs) + ((hv, hl) if entropy == "cavlc" else ())
        if damage_bucket > 0:
            xs = xs + (rows,)
        if tune == "hq":
            # 1-frame lookahead over the staged ring: frame k pre-biases
            # its qp plane with frame k+1 (the last frame sees itself —
            # the full static bias, mirrored by models/h264._ring_flush)
            lead = frames_xs[0]
            xs = xs + (jnp.concatenate([lead[1:], lead[-1:]], axis=0),)
        (ry, rcb, rcr), (flats, mvs, lvs) = lax.scan(
            body, (ref_y, ref_cb, ref_cr), xs)
        prefix = flats if prefix_len <= 0 else flats[:, :prefix_len]
        return flats, prefix, ry, rcb, rcr, mvs, lvs

    from .h264_inter import RING_DONATE

    if ingest == "rgb":
        @functools.partial(jax.jit, donate_argnames=RING_DONATE)
        def chunk_step(rgbs, ref_y, ref_cb, ref_cr, hv=None, hl=None):
            return scan_chunk((rgbs,), ref_y, ref_cb, ref_cr, hv, hl)
    else:
        @functools.partial(jax.jit, donate_argnames=RING_DONATE)
        def chunk_step(ys, cbs, crs, ref_y, ref_cb, ref_cr,
                       hv=None, hl=None, rows=None):
            return scan_chunk((ys, cbs, crs), ref_y, ref_cb, ref_cr,
                              hv, hl, rows)
    return chunk_step


@jax.jit
def _probe_loop(x, steps):
    """Trivial device-resident loop for the link probe: the work is a few
    integer adds (sub-microsecond on any backend), so the wall-clock of a
    small-k call is dominated by dispatch + the 4-byte result pull — i.e.
    by the host<->device link, not by compute."""
    def body(i, acc):
        return acc + x[i % 8, i % 8].astype(jnp.uint32)

    return lax.fori_loop(0, steps, body, jnp.uint32(0))


def measure_link_rtt(reps: int = 7, k_hi: int = 257) -> dict:
    """Estimate the host<->device round-trip cost of one dispatch+pull.

    Same differencing trick as :func:`measure_steady_state`, inverted:
    ``t(k) = rtt + k * step`` — two trip counts give ``step``, and
    ``rtt = t_lo - k_lo * step`` is the fixed per-call cost (dispatch,
    transfer-out of the 4-byte checksum, tunnel RTT where one exists).
    This is the number the serving-budget ledger subtracts from the
    collect stage to separate link cost from compute (obs/budget).

    Returns {"rtt_ms", "step_us", "samples"}; rtt_ms is the median of
    ``reps`` k=1 calls minus the per-step cost.
    """
    x = jax.device_put(np.zeros((8, 8), np.uint8))
    np.asarray(_probe_loop(x, jnp.int32(1)))          # compile + warm
    lo = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(_probe_loop(x, jnp.int32(1)))
        lo.append(time.perf_counter() - t0)
    t0 = time.perf_counter()
    np.asarray(_probe_loop(x, jnp.int32(k_hi)))
    t_hi = time.perf_counter() - t0
    lo_sorted = sorted(lo)
    t_lo = lo_sorted[len(lo_sorted) // 2]             # median: RTT jitters
    step_s = max((t_hi - t_lo) / (k_hi - 1), 0.0)
    rtt_s = max(t_lo - step_s, 0.0)
    return {"rtt_ms": round(rtt_s * 1e3, 3),
            "step_us": round(step_s * 1e6, 3),
            "samples": [round(v * 1e3, 3) for v in lo_sorted]}


def measure_steady_state(loop_fn, *, budget_s: float = 60.0,
                         k_lo: int = 4) -> dict:
    """Run ``loop_fn(steps)->checksum`` at two trip counts and difference.

    ``loop_fn`` must accept a Python int and block until the checksum is on
    the host (a 4-byte pull).  Returns {"step_ms", "fps", "k_hi"}.
    Trip counts are chosen adaptively so the measured signal dominates
    tunnel/RTT noise while staying inside ``budget_s``.
    """
    loop_fn(1)                                   # compile + warm
    t0 = time.perf_counter()
    loop_fn(k_lo)
    t_lo_probe = time.perf_counter() - t0
    # Pick k_hi for a good signal inside the budget.  The two timed()
    # calls below realize ~2 * (2 reps) * k_hi steps total, so size one
    # k_hi call at ~budget/5 and NEVER floor above what the budget buys —
    # on a slow backend (CPU fallback: seconds/step) an unconditional
    # 8*k_lo floor would blow straight through the caller's watchdog.
    per_step_guess = max(t_lo_probe / k_lo, 1e-5)
    k_budget = int(0.2 * budget_s / per_step_guess)
    k_hi = max(k_lo + 1, min(k_budget, 4096))

    def timed(k, reps=2):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            loop_fn(k)
            best = min(best, time.perf_counter() - t0)
        return best

    t_lo = timed(k_lo)
    t_hi = timed(k_hi)
    step_s = max((t_hi - t_lo) / (k_hi - k_lo), 1e-9)
    return {"step_ms": round(step_s * 1e3, 3),
            "fps": round(1.0 / step_s, 1),
            "k_hi": k_hi}


def capture_cost_analysis(name: str, jitted, *args, **static_kw) -> dict:
    """Lower+compile ``jitted`` for ``args`` and publish XLA's cost
    analysis (flops, bytes accessed, utilization) into the kernel
    profiler (obs/profile) under ``name``.

    This is the static half of the profiling plane: the histograms say
    what a stage COSTS on the wall clock, the cost analysis says what
    XLA thinks the computation IS — together they separate "the kernel
    got slower" from "the kernel got bigger".  Compiling here is a
    cache hit whenever the serving path already jitted the same shapes,
    so calling it after a warmup round is effectively free.

    Returns the captured dict ({} when the backend exposes none).
    """
    from ..obs.profile import PROFILER

    try:
        lowered = jitted.lower(*args, **static_kw)
        costs = lowered.compile().cost_analysis()
    except Exception:
        return {}
    # jax versions disagree on list-of-dicts vs dict
    info = costs[0] if isinstance(costs, (list, tuple)) and costs else costs
    if not isinstance(info, dict):
        return {}
    PROFILER.note_cost_analysis(name, info)
    return info
