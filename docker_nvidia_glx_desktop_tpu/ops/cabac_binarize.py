"""Device-side CABAC binarization + context-index derivation.

Round 5 left the CABAC serving split as: device transform/quant +
``ops/level_pack`` compaction, host C doing EVERYTHING entropy — dense
level scan, binarization, ctxIdx derivation, arithmetic engine.  The
host stage measured 57-72 ms single-core at 1080p (BENCH_r05), which no
core count rescues to 60 fps without shrinking the per-row work.

This module moves binarization and ctxIdx computation onto the device:
a pure-JAX kernel walks the H.264 CABAC syntax (spec 9.3.2/9.3.3) for
every macroblock IN PARALLEL and emits a packed record stream — the
exact (bin, ctxIdx, bypass) sequence the arithmetic engine must
consume — through the same scatter-free bitmerge hierarchy level_pack
uses.  The host (native/cabac.cpp ``h264_cabac_engine_rows``) then runs
ONLY the arithmetic engine: read record, update range/low, emit bits.
No dense level tensors cross the link and the host never re-derives a
context.

Why this needs no sequential scans: under slice-per-MB-row every
context dependency is either *within* the MB (static block geometry) or
on the LEFT MB's *input data* (its levels/mv decide its cbf/cbp/skip/
mvd — never its coded output), so the whole derivation is shifts and
wheres over (R, C, ...) tensors.  Residual blocks are traced ONCE with
a leading block axis (16 luma / 8 chroma-AC blocks share one op set),
keeping the XLA graph small.

Record wire format (MSB-first bits inside each variable-length slot;
zero-length slots vanish — bitmerge drops them):

  DEC  ``0``   + ctx(9) + bin(1)             11 bits  one decision
  RUN  ``10``  + ctx(9) + cnt(4)             15 bits  cnt 1-bins on ctx
  BYP  ``110`` + cnt(4) + bits(cnt)        7+cnt bits bypass bins
  TRM  ``111`` + bin(1)                       4 bits  terminate

Transport layout (uint32 words; level_pack's shape with version 2 and
per-row BIT counts, so the engine knows exactly where a row's records
end — the zero-padded word tail must not read as a DEC record):

  [0] version (2)   [1] overflow flag   [2] total payload words
  [3] rows R        [4] slots per MB    [5..7] reserved
  [META_WORDS .. META_WORDS+R)   per-row payload BIT counts
  [META_WORDS+R ..)              row payloads, word-aligned

Overflow (a |level| beyond the suffix-slot budget, or a pathological
MB overrunning the static per-MB bit cap) sets the flag; the caller
falls back to the dense host coder for that frame — correctness never
depends on the fast path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import bitmerge

__all__ = ["META_WORDS", "binarize_p", "binarize_intra", "split_rows",
           "header_words", "payload_words", "decode_records_py",
           "stitch_rows"]

META_WORDS = 8

# ctxBlockCat offsets (bitstream/cabac.py is the value source)
_CBF_OFF = {0: 0, 1: 4, 2: 8, 3: 12, 4: 16}
_SIG_OFF = {0: 0, 1: 15, 2: 29, 3: 44, 4: 47}
_ABS_OFF = {0: 0, 1: 10, 2: 20, 3: 30, 4: 39}

# luma4x4BlkIdx -> (bx, by) z-scan (bitstream/cabac._BLK_XY)
_BLK_XY = ((0, 0), (1, 0), (0, 1), (1, 1),
           (2, 0), (3, 0), (2, 1), (3, 1),
           (0, 2), (1, 2), (0, 3), (1, 3),
           (2, 2), (3, 2), (2, 3), (3, 3))

_U32 = jnp.uint32


def _u(x):
    return jnp.asarray(x).astype(_U32)


def _i(x):
    return jnp.asarray(x).astype(jnp.int32)


def _dec(ctx, b, pres=None):
    """DEC record: tag 0 + ctx(9) + bin(1)."""
    val = (_u(ctx) << 1) | _u(jnp.asarray(b).astype(bool))
    if pres is None:
        return val, jnp.broadcast_to(jnp.int32(11), val.shape)
    val, pres = jnp.broadcast_arrays(val, pres)
    return val, jnp.where(pres, 11, 0).astype(jnp.int32)


def _run(ctx, cnt, pres):
    """RUN record: tag 10 + ctx(9) + cnt(4): cnt decisions of bin=1."""
    val = (_u(2) << 13) | (_u(ctx) << 4) | _u(cnt)
    val, pres = jnp.broadcast_arrays(val, pres)
    return val, jnp.where(pres, 15, 0).astype(jnp.int32)


def _byp(bits, cnt, pres):
    """BYP record: tag 110 + cnt(4) + cnt literal bypass bins."""
    cnt = _u(cnt)
    val = (_u(6) << (4 + cnt)) | (cnt << cnt) | _u(bits)
    val, pres = jnp.broadcast_arrays(val, pres)
    return val, jnp.where(pres, 7 + _i(cnt), 0).astype(jnp.int32)


def _trm(b, pres=None):
    """TRM record: tag 111 + bin."""
    val = (_u(7) << 1) | _u(jnp.asarray(b).astype(bool))
    if pres is None:
        return val, jnp.broadcast_to(jnp.int32(4), val.shape)
    val, pres = jnp.broadcast_arrays(val, pres)
    return val, jnp.where(pres, 4, 0).astype(jnp.int32)


def _cat(a, b):
    """Concatenate two records into one slot (either may be absent)."""
    av, al = a
    bv, bl = b
    av, al, bv, bl = jnp.broadcast_arrays(av, al, bv, bl)
    val = (jnp.where(al > 0, av << bl.astype(_U32), 0)
           | jnp.where(bl > 0, bv, 0))
    return val.astype(_U32), (al + bl).astype(jnp.int32)


def _merge(a, b):
    """Merge two mutually-exclusive slot candidates (at most one has a
    nonzero length per MB) into one slot."""
    av, al = a
    bv, bl = b
    av, al, bv, bl = jnp.broadcast_arrays(av, al, bv, bl)
    return jnp.where(bl > 0, bv, av).astype(_U32), (al + bl)


class _Recs:
    """Slot accumulator: (R, C, k)-piece list concatenated at pack
    time, plus the STATIC per-MB maximum bit total (the L2 cap)."""

    def __init__(self, shape):
        self.shape = shape
        self.pieces = []
        self.max_bits = 0

    def add(self, rec, mx: int):
        v, ln = rec
        self.pieces.append(
            (jnp.broadcast_to(v, self.shape)[..., None].astype(_U32),
             jnp.broadcast_to(ln, self.shape)[..., None]
             .astype(jnp.int32)))
        self.max_bits += mx

    def add_batch(self, vals, lns, mx_total: int):
        """vals/lns (R, C, K): K pre-stacked slots in stream order."""
        self.pieces.append((vals.astype(_U32), lns.astype(jnp.int32)))
        self.max_bits += mx_total

    def stacked(self):
        return (jnp.concatenate([p[0] for p in self.pieces], axis=-1),
                jnp.concatenate([p[1] for p in self.pieces], axis=-1))


def _residual_slots(coeffs, cat: int, cbf_inc, emit):
    """Record slots for residual blocks (spec 9.3.3.1.3), traced once
    over arbitrary leading dims (batch the block axis!).

    coeffs (..., n) int32 zigzag; cbf_inc/emit (...,).  Returns
    (vals (..., S), lns (..., S), value_overflow (...,), max_bits) with
    S = 1 + (n-1) + 3n: cbf, sig+last pairs, then per-coefficient
    [first-prefix-bin][run+terminator][suffix+sign] in reverse scan
    order — exactly the engine's consumption order."""
    n = coeffs.shape[-1]
    nz = coeffs != 0
    cbf = nz.any(-1)
    idx = jnp.arange(n, dtype=jnp.int32)
    last_nz = jnp.max(jnp.where(nz, idx, -1), axis=-1)
    vals, lns = [], []
    maxb = 0

    def add(rec, mx):
        nonlocal maxb
        v, ln = rec
        vals.append(v)
        lns.append(ln)
        maxb += mx

    add(_dec(85 + _CBF_OFF[cat] + _i(cbf_inc), cbf, emit), 11)
    sig_base = 105 + _SIG_OFF[cat]
    last_base = 166 + _SIG_OFF[cat]
    for i in range(n - 1):
        inc = min(i, 2) if cat == 3 else i
        pres = emit & cbf & (i <= last_nz)
        d_sig = _dec(sig_base + inc, nz[..., i], pres)
        d_last = _dec(last_base + inc, last_nz == i, pres & nz[..., i])
        add(_cat(d_sig, d_last), 22)

    a = jnp.abs(coeffs)
    lvl = a - 1

    def after(x):            # count over scan positions > i
        x = x.astype(jnp.int32)
        rev = jnp.cumsum(x[..., ::-1], axis=-1)[..., ::-1]
        return rev - x

    num_gt1 = after(nz & (a > 1))
    num_eq1 = after(a == 1)
    abs_base = 227 + _ABS_OFF[cat]
    capn = 3 if cat == 3 else 4
    c0 = abs_base + jnp.where(num_gt1 > 0, 0,
                              jnp.minimum(4, 1 + num_eq1))
    cn = abs_base + 5 + jnp.minimum(capn, num_gt1)
    prefix = jnp.minimum(lvl, 14)
    # UEG0 suffix (lvl >= 14) + sign, as bypass runs.  DC categories
    # (0, 3) carry the Hadamard-amplified magnitudes, so they get a
    # TWO-slot suffix budget (|level| <= 16398, past level_pack's own
    # +-16383 value cap); AC categories keep one slot (|level| <= 141 —
    # beyond it only at pathological qp, where the per-frame dense
    # fallback takes over).
    wide = cat in (0, 3)
    u_lim = 14 if wide else 6
    v = jnp.maximum(lvl - 14, 0)
    u = jnp.zeros_like(v)
    for k in range(1, u_lim + 2):
        u = u + (v + 1 >= (1 << k))
    u = jnp.minimum(u, u_lim)          # past-limit flags overflow below
    r = v - ((1 << u) - 1)
    sign = (coeffs < 0).astype(jnp.int32)
    suf = (((1 << u) - 1) << (u + 1)) | r
    has_suf = lvl >= 14
    bits = jnp.where(has_suf, (suf << 1) | sign, sign)
    cnt = jnp.where(has_suf, 2 * u + 2, 1)
    if wide:
        hi_len = jnp.minimum(cnt, 15)
        lo_len = cnt - hi_len
        hi_bits = bits >> lo_len
        lo_bits = bits & ((1 << lo_len) - 1)
    zero = jnp.zeros(coeffs.shape[:-1], bool)

    for j in range(n - 1, -1, -1):            # reverse scan order
        nzj = emit & nz[..., j]
        add(_dec(c0[..., j], lvl[..., j] >= 1, nzj), 11)
        run = _run(cn[..., j], jnp.clip(prefix[..., j] - 1, 1, 14),
                   nzj & (prefix[..., j] >= 2))
        term = _dec(cn[..., j], zero,
                    nzj & (prefix[..., j] >= 1) & (prefix[..., j] < 14))
        add(_cat(run, term), 26)
        if wide:
            add(_byp(hi_bits[..., j], hi_len[..., j], nzj), 22)
            add(_byp(lo_bits[..., j], jnp.maximum(lo_len[..., j], 1),
                     nzj & (lo_len[..., j] > 0)), 22)
        else:
            add(_byp(bits[..., j], cnt[..., j], nzj), 22)
    ovf = (emit[..., None] & nz
           & (jnp.maximum(lvl - 14, 0) + 1 > (1 << (u_lim + 1)) - 1)
           ).any(-1)
    return jnp.stack(vals, -1), jnp.stack(lns, -1), ovf, maxb


def _left(x):
    """Left-MB shift along the column axis (column 0 gets zeros)."""
    return jnp.concatenate([jnp.zeros_like(x[:, :1]), x[:, :-1]], axis=1)


def _chroma_cbp(cb_dc, cb_ac, cr_dc, cr_ac):
    c_dc = cb_dc.any(-1) | cr_dc.any(-1)
    c_ac = cb_ac.any((-2, -1)) | cr_ac.any((-2, -1))
    return jnp.where(c_ac, 2, jnp.where(c_dc, 1, 0))


def _raster_grid(blk16):
    """(R, C, 16) per-blkIdx values -> (R, C, 4, 4) raster [by][bx]."""
    nr, nc = blk16.shape[:2]
    g = jnp.zeros((nr, nc, 4, 4), blk16.dtype)
    for blk, (bx, by) in enumerate(_BLK_XY):
        g = g.at[..., by, bx].set(blk16[..., blk])
    return g


def _luma_cbf_inc(cbf_r, left_skip, col0, intra: bool):
    """ctxIdxInc of coded_block_flag for the 16 luma blocks, stacked
    (R, C, 16) in blkIdx order.  cbf_r (R, C, 4, 4) raster grid."""
    una = 1 if intra else 0
    left_c3 = [_left(cbf_r[..., by, 3].astype(jnp.int32))
               for by in range(4)]
    out = []
    for blk, (bx, by) in enumerate(_BLK_XY):
        if bx > 0:
            av = cbf_r[..., by, bx - 1].astype(jnp.int32)
        else:
            av = jnp.where(col0, una,
                           jnp.where(left_skip, 0, left_c3[by]))
        bv = (cbf_r[..., by - 1, bx].astype(jnp.int32) if by > 0
              else jnp.full_like(av, una))
        out.append(av + 2 * bv)
    return jnp.stack(out, -1)


def _chroma_slots(recs, cb_dc, cb_ac, cr_dc, cr_ac, cc, left_skip, col0,
                  emit_any, intra: bool):
    """Chroma DC (cat3) then AC (cat4) residual slots, coder order —
    both traced once over a stacked block axis."""
    una = 1 if intra else 0
    emit_dc = emit_any & (cc > 0)
    emit_ac = emit_any & (cc == 2)
    # DC: (R, C, 2, 4) -- cb then cr, matching _code_chroma order
    dc = jnp.stack([cb_dc, cr_dc], axis=2)
    dcnz = dc.any(-1).astype(jnp.int32)                  # (R, C, 2)
    a = jnp.where(col0[..., None], una,
                  jnp.where(left_skip[..., None], 0, _left(dcnz)))
    v, ln, ovf_dc, mx = _residual_slots(dc, 3, a + 2 * una,
                                        emit_dc[..., None])
    nr, nc = cc.shape
    recs.add_batch(v.reshape(nr, nc, -1), ln.reshape(nr, nc, -1),
                   2 * mx)
    # AC: (R, C, 8, 15) -- cb blocks 0..3 then cr blocks 0..3
    ac = jnp.concatenate([cb_ac, cr_ac], axis=2)
    acnz = ac.any(-1).astype(jnp.int32)                  # (R, C, 8)
    incs = []
    for p in range(2):
        for b in range(4):
            by, bx = divmod(b, 2)
            cur = acnz[..., p * 4:p * 4 + 4]
            if bx > 0:
                av = cur[..., by * 2]
            else:
                av = jnp.where(col0, una,
                               jnp.where(left_skip, 0,
                                         _left(cur[..., by * 2 + 1])))
            bv = cur[..., bx] if by > 0 else jnp.full_like(av, una)
            incs.append(av + 2 * bv)
    v, ln, ovf_ac, mx = _residual_slots(ac, 4, jnp.stack(incs, -1),
                                        emit_ac[..., None])
    recs.add_batch(v.reshape(nr, nc, -1), ln.reshape(nr, nc, -1),
                   8 * mx)
    return ovf_dc.any(-1) | ovf_ac.any(-1)


def _mvd_slots(recs, mvd_comp, s_left, base: int, pres):
    """mvd_l0 component: UEG3 uCoff=9 prefix (paired DECs) + suffix/
    sign bypass.  Returns the suffix-budget overflow mask."""
    inc = jnp.where(s_left < 3, 0, jnp.where(s_left <= 32, 1, 2))
    aa = jnp.abs(mvd_comp)
    prefix = jnp.minimum(aa, 9)
    ctxs = [base + inc, base + 3, base + 4, base + 5, base + 6]
    ds = []
    for k in range(9):
        pk = pres & ((k < prefix) | ((k == prefix) & (prefix < 9)))
        ds.append(_dec(ctxs[min(k, 4)], k < prefix, pk))
    for k in range(0, 8, 2):
        recs.add(_cat(ds[k], ds[k + 1]), 22)
    recs.add(ds[8], 11)
    v3 = jnp.maximum(aa - 9, 0)
    u3 = jnp.zeros_like(v3)
    for j in range(1, 7):
        u3 = u3 + (v3 >= 8 * ((1 << j) - 1))
    r3 = v3 - 8 * ((1 << u3) - 1)
    suf3 = (((1 << u3) - 1) << (u3 + 4)) | r3
    sign = (mvd_comp < 0).astype(jnp.int32)
    has_suf = aa >= 9
    bits = jnp.where(has_suf, (suf3 << 1) | sign, sign)
    cnt = jnp.where(has_suf, 2 * u3 + 5, 1)
    recs.add(_byp(bits, cnt, pres & (aa > 0)), 22)
    return pres & (2 * u3 + 5 > 15)


def _pack_stream(recs: _Recs, value_ovf):
    """Slot arrays -> bitmerge hierarchy -> version-2 transport buffer
    (per-row BIT counts in the meta table)."""
    vals, lns = recs.stacked()
    r, c, s = vals.shape
    pad = (-s) % 8
    if pad:
        vals = jnp.pad(vals, ((0, 0), (0, 0), (0, pad)))
        lns = jnp.pad(lns, ((0, 0), (0, 0), (0, pad)))
        s += pad
    nb = s // 8
    w1, nb1, _ = bitmerge.slots_to_words(
        vals.reshape(r, c, nb, 8), lns.reshape(r, c, nb, 8), 8)
    p2 = 1 << int(np.ceil(np.log2(nb)))
    w1 = jnp.pad(w1, ((0, 0), (0, 0), (0, p2 - nb), (0, 0)))
    nb1 = jnp.pad(nb1, ((0, 0), (0, 0), (0, p2 - nb)))
    w2, mb_bits = bitmerge.merge_pieces_tree(w1, nb1)
    mb_cap = min(p2 * 8, -(-recs.max_bits // 32))
    overflow = value_ovf.any() | (mb_bits > 32 * mb_cap).any()
    w2 = w2[..., :mb_cap]
    c2 = 1 << int(np.ceil(np.log2(c)))
    w2 = jnp.pad(w2, ((0, 0), (0, c2 - c), (0, 0)))
    mb_bits = jnp.pad(mb_bits, ((0, 0), (0, c2 - c)))
    w3, row_bits = bitmerge.merge_pieces_tree(w2, mb_bits)
    row_words = ((row_bits + 31) >> 5).astype(jnp.int32)
    row_cap = w3.shape[-1]

    hdr = jnp.zeros(META_WORDS + r, jnp.uint32)
    hdr = (hdr.at[0].set(2)
           .at[1].set(overflow.astype(jnp.uint32))
           .at[2].set(row_words.sum().astype(jnp.uint32))
           .at[3].set(r).at[4].set(s)
           .at[META_WORDS:].set(row_bits.astype(jnp.uint32)))
    offs = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(row_words)])[:r]
    payload = jnp.zeros(r * row_cap, jnp.uint32)

    def body(i, acc):
        return jax.lax.dynamic_update_slice(
            acc, jax.lax.dynamic_index_in_dim(w3, i, keepdims=False),
            (offs[i],))

    payload = jax.lax.fori_loop(0, r, body, payload)
    return jnp.concatenate([hdr, payload])


@jax.jit
def binarize_p(mv, luma, cb_dc, cb_ac, cr_dc, cr_ac):
    """Record stream for a P picture (P_L0_16x16 + P_Skip subset).

    Shapes as ops/h264_inter output (mv (R,C,2) quarter-pel (y, x),
    luma (R,C,16,16) zigzag, chroma DC/AC).  Returns the transport
    buffer the host engine replays row by row."""
    mv = _i(mv)
    luma = _i(luma)
    cb_dc, cb_ac = _i(cb_dc), _i(cb_ac)
    cr_dc, cr_ac = _i(cr_dc), _i(cr_ac)
    nr, nc = luma.shape[:2]
    recs = _Recs((nr, nc))
    col0 = jnp.broadcast_to(jnp.arange(nc) == 0, (nr, nc))

    lnz = luma.any(-1)                                 # (R, C, 16)
    grp = lnz.reshape(nr, nc, 4, 4).any(-1)            # (R, C, 4) 8x8
    cbp_luma = (grp * (1 << jnp.arange(4))).sum(-1)
    cc = _chroma_cbp(cb_dc, cb_ac, cr_dc, cr_ac)
    skip = (mv == 0).all(-1) & (cbp_luma == 0) & (cc == 0)
    left_skip = _left(skip)
    ns = ~skip

    mvp = _left(mv)                 # left MB's mv (a skip left's is 0)
    mvd = mv - mvp
    absmvd = jnp.abs(mvd)
    labs = _left(jnp.where(skip[..., None], 0, absmvd))

    # mb_skip_flag
    inc_skip = ((~col0) & (~left_skip)).astype(jnp.int32)
    recs.add(_dec(11 + inc_skip, skip), 11)
    # mb_type P_L0_16x16: "000" on ctx 14, 15, 16
    f = jnp.zeros((nr, nc), bool)
    recs.add(_cat(_dec(14, f, ns), _dec(15, f, ns)), 22)
    recs.add(_dec(16, f, ns), 11)
    # mvd_l0: comp 0 = x (mv[..., 1]), comp 1 = y (mv[..., 0])
    ovf = _mvd_slots(recs, mvd[..., 1], labs[..., 1], 40, ns)
    ovf |= _mvd_slots(recs, mvd[..., 0], labs[..., 0], 47, ns)
    # coded_block_pattern
    lcl = _left(jnp.where(skip, 0, cbp_luma))
    lcc = _left(jnp.where(skip, 0, cc))
    cbp_d = []
    for b in range(4):
        if b & 1:
            a_n = 1 - grp[..., b - 1].astype(jnp.int32)
        else:
            a_n = jnp.where(col0, 0, 1 - ((lcl >> (b + 1)) & 1))
        b_n = (1 - grp[..., b - 2].astype(jnp.int32)) if b & 2 \
            else jnp.zeros((nr, nc), jnp.int32)
        cbp_d.append(_dec(73 + a_n + 2 * b_n, grp[..., b], ns))
    recs.add(_cat(cbp_d[0], cbp_d[1]), 22)
    recs.add(_cat(cbp_d[2], cbp_d[3]), 22)
    d1 = _dec(77 + (lcc > 0).astype(jnp.int32), cc > 0, ns)
    d2 = _dec(81 + (lcc == 2).astype(jnp.int32), cc == 2,
              ns & (cc > 0))
    recs.add(_cat(d1, d2), 22)
    # mb_qp_delta (always 0; prev MB's delta is 0 too -> ctx 60)
    recs.add(_dec(60, f, ns & ((cbp_luma > 0) | (cc > 0))), 11)
    # luma residuals, all 16 blocks in one traced batch
    incs = _luma_cbf_inc(_raster_grid(lnz), left_skip, col0,
                         intra=False)
    emit16 = ns[..., None] & jnp.repeat(grp, 4, axis=-1)
    v, ln, ov, mx = _residual_slots(luma, 2, incs, emit16)
    recs.add_batch(v.reshape(nr, nc, -1), ln.reshape(nr, nc, -1),
                   16 * mx)
    ovf |= ov.any(-1)
    # chroma residuals
    ovf |= _chroma_slots(recs, cb_dc, cb_ac, cr_dc, cr_ac, cc,
                         left_skip, col0, ns, intra=False)
    # end_of_slice_flag
    recs.add(_trm(jnp.broadcast_to(jnp.arange(nc) == nc - 1,
                                   (nr, nc))), 4)
    return _pack_stream(recs, ovf)


@jax.jit
def binarize_intra(luma_dc, luma_ac, cb_dc, cb_ac, cr_dc, cr_ac,
                   pred_mode, mb_i4, i4_modes, luma_i4):
    """Record stream for an I picture (I_16x16 + I_NxN subset)."""
    luma_dc, luma_ac = _i(luma_dc), _i(luma_ac)
    cb_dc, cb_ac = _i(cb_dc), _i(cb_ac)
    cr_dc, cr_ac = _i(cr_dc), _i(cr_ac)
    pred_mode = _i(pred_mode)
    mb_i4 = jnp.asarray(mb_i4).astype(bool)
    i4_modes = _i(i4_modes)
    luma_i4 = _i(luma_i4)
    nr, nc = luma_dc.shape[:2]
    recs = _Recs((nr, nc))
    col0 = jnp.broadcast_to(jnp.arange(nc) == 0, (nr, nc))
    f = jnp.zeros((nr, nc), bool)
    left_skip = f                                  # no skip in I slices

    cl16 = luma_ac.any((-2, -1))                   # I16 AC coded flag
    i4nz = luma_i4.any(-1)                         # (R, C, 16)
    grp4 = i4nz.reshape(nr, nc, 4, 4).any(-1)      # (R, C, 4)
    cbp4 = (grp4 * (1 << jnp.arange(4))).sum(-1)
    cc = _chroma_cbp(cb_dc, cb_ac, cr_dc, cr_ac)
    i16 = ~mb_i4

    # mb_type prefix: ctx 3 + (left available && left is I_16x16)
    linc = ((~col0) & _left(i16)).astype(jnp.int32)
    recs.add(_dec(3 + linc, i16), 11)
    # I_16x16 suffix: not-PCM terminate + cbp/pred bins
    recs.add(_trm(f, i16), 4)
    recs.add(_cat(_dec(6, cl16, i16), _dec(7, cc > 0, i16)), 22)
    recs.add(_dec(8, cc == 2, i16 & (cc > 0)), 11)
    recs.add(_cat(_dec(9, (pred_mode >> 1) & 1, i16),
                  _dec(10, pred_mode & 1, i16)), 22)
    # I_NxN: prev_intra4x4_pred_mode + rem bins (8.3.1.1 predictors)
    modes_r = _raster_grid(jnp.where(mb_i4[..., None], i4_modes, 2))
    left_m3 = [_left(modes_r[..., by, 3]) for by in range(4)]
    for blk, (bx, by) in enumerate(_BLK_XY):
        if bx > 0:
            ma = modes_r[..., by, bx - 1]
            ava = jnp.ones((nr, nc), bool)
        else:
            ma = jnp.where(col0, 2, left_m3[by])
            ava = ~col0
        if by > 0:
            mb_, avb = modes_r[..., by - 1, bx], jnp.ones((nr, nc), bool)
        else:
            mb_, avb = jnp.full((nr, nc), 2), f
        pred = jnp.where(ava & avb, jnp.minimum(ma, mb_), 2)
        mode = i4_modes[..., blk]
        eq = mode == pred
        rem = jnp.where(mode > pred, mode - 1, mode)
        e4 = mb_i4
        recs.add(_cat(_dec(68, eq, e4), _dec(69, rem & 1, e4 & ~eq)),
                 22)
        recs.add(_cat(_dec(69, (rem >> 1) & 1, e4 & ~eq),
                      _dec(69, (rem >> 2) & 1, e4 & ~eq)), 22)
    # intra_chroma_pred_mode (always DC; left term identically 0)
    recs.add(_dec(64, f), 11)
    # coded_block_pattern (I_NxN only)
    lcl = _left(jnp.where(mb_i4, cbp4, jnp.where(cl16, 0xF, 0)))
    lcc = _left(cc)
    cbp_d = []
    for b in range(4):
        if b & 1:
            a_n = 1 - grp4[..., b - 1].astype(jnp.int32)
        else:
            a_n = jnp.where(col0, 0, 1 - ((lcl >> (b + 1)) & 1))
        b_n = (1 - grp4[..., b - 2].astype(jnp.int32)) if b & 2 \
            else jnp.zeros((nr, nc), jnp.int32)
        cbp_d.append(_dec(73 + a_n + 2 * b_n, grp4[..., b], mb_i4))
    recs.add(_cat(cbp_d[0], cbp_d[1]), 22)
    recs.add(_cat(cbp_d[2], cbp_d[3]), 22)
    d1 = _dec(77 + (lcc > 0).astype(jnp.int32), cc > 0, mb_i4)
    d2 = _dec(81 + (lcc == 2).astype(jnp.int32), cc == 2,
              mb_i4 & (cc > 0))
    recs.add(_cat(d1, d2), 22)
    # mb_qp_delta: I16 always codes it; I_NxN only when cbp nonzero
    recs.add(_dec(60, f, i16 | ((cbp4 > 0) | (cc > 0))), 11)
    # luma DC (cat 0, I16 only): left term requires a left I16 MB
    dcnz = luma_dc.any(-1).astype(jnp.int32)
    a = jnp.where(col0, 1, jnp.where(_left(i16), _left(dcnz), 0))
    v, ln, ov, mx = _residual_slots(luma_dc, 0, a + 2, i16)
    recs.add_batch(v, ln, mx)
    ovf = ov
    # luma blocks: I16 AC (cat 1, n=15) and I_NxN (cat 2, n=16) share a
    # 64-slot region per block (mutually exclusive per MB), both traced
    # once over the 16-block axis
    cbf_blk = jnp.where(mb_i4[..., None], i4nz, luma_ac.any(-1))
    incs = _luma_cbf_inc(_raster_grid(cbf_blk), left_skip, col0,
                         intra=True)
    v16, l16, ov16, _ = _residual_slots(
        luma_ac, 1, incs, (i16 & cl16)[..., None])
    v4, l4, ov4, mx4 = _residual_slots(
        luma_i4, 2, incs,
        mb_i4[..., None] & jnp.repeat(grp4, 4, axis=-1))
    padk = v4.shape[-1] - v16.shape[-1]               # cat1 is 4 short
    v16 = jnp.pad(v16, ((0, 0),) * 3 + ((0, padk),))
    l16 = jnp.pad(l16, ((0, 0),) * 3 + ((0, padk),))
    vm, lm = _merge((v16, l16), (v4, l4))
    recs.add_batch(vm.reshape(nr, nc, -1), lm.reshape(nr, nc, -1),
                   16 * mx4)
    ovf |= ov16.any(-1) | ov4.any(-1)
    # chroma residuals
    ovf |= _chroma_slots(recs, cb_dc, cb_ac, cr_dc, cr_ac, cc,
                         left_skip, col0, jnp.ones((nr, nc), bool),
                         intra=True)
    recs.add(_trm(jnp.broadcast_to(jnp.arange(nc) == nc - 1,
                                   (nr, nc))), 4)
    return _pack_stream(recs, ovf)


# ---------------------------------------------------------------------------
# Host-side helpers
# ---------------------------------------------------------------------------

def header_words(rows: int) -> int:
    return META_WORDS + rows


def payload_words(head: np.ndarray) -> int:
    return int(head[2])


def split_rows(buf: np.ndarray, rows: int):
    """Transport buffer (host array covering header + payload) ->
    (payload uint32, row_off int64 (rows+1,), row_bits int64) or None
    on the overflow flag."""
    head = buf[:META_WORDS + rows]
    assert int(head[0]) == 2, "cabac_binarize version mismatch"
    if int(head[1]):
        return None
    row_bits = head[META_WORDS:META_WORDS + rows].astype(np.int64)
    row_words = (row_bits + 31) >> 5
    row_off = np.zeros(rows + 1, np.int64)
    np.cumsum(row_words, out=row_off[1:])
    payload = np.ascontiguousarray(
        buf[META_WORDS + rows:META_WORDS + rows + int(row_off[-1])],
        dtype=np.uint32)
    return payload, row_off, row_bits


def stitch_rows(bufs, rows_each) -> np.ndarray:
    """Stitch per-shard transport buffers into one whole-frame buffer.

    Every cross-MB context in the record kernels above is a ``_left``
    shift WITHIN a row (slice-per-MB-row makes vertical neighbors
    unavailable), so a shard covering a contiguous block of MB rows
    emits exactly the rows a whole-frame binarize would — stitching is
    pure row concatenation: one header, the shards' per-row BIT tables
    back to back, then their word-aligned row payloads back to back.
    This is the L4 of the bitmerge hierarchy (slot -> block -> MB ->
    row -> FRAME), run on the host because the shards live on different
    chips.  The host engine replays the stitched buffer exactly as a
    single-device one (byte-identical AU; tests/test_spatial.py).

    ``bufs``: per-shard buffers in row order (each covering
    ``rows_each`` MB rows; an int or a per-shard sequence).  A shard's
    overflow flag poisons the stitched header (minimal flag-only
    buffer) so callers fall into the dense path without reading
    garbage row tables.
    """
    heads = [np.asarray(b) for b in bufs]
    if isinstance(rows_each, int):
        rows_each = [rows_each] * len(heads)
    total_rows = int(sum(rows_each))
    out_head = np.zeros(META_WORDS, np.uint32)
    out_head[0] = 2
    out_head[3] = total_rows
    out_head[4] = heads[0][4]
    if any(int(h[1]) for h in heads):
        out_head[1] = 1                      # overflow: flag-only
        return np.concatenate(
            [out_head, np.zeros(total_rows, np.uint32)])
    bit_tables, payloads = [], []
    total_words = 0
    for h, r in zip(heads, rows_each):
        assert int(h[0]) == 2, "cabac_binarize version mismatch"
        assert int(h[3]) == r, "shard row count disagrees with layout"
        row_bits = h[META_WORDS:META_WORDS + r]
        n = int(((row_bits.astype(np.int64) + 31) >> 5).sum())
        bit_tables.append(row_bits.astype(np.uint32))
        payloads.append(h[META_WORDS + r:META_WORDS + r + n]
                        .astype(np.uint32))
        total_words += n
    out_head[2] = total_words
    return np.concatenate([out_head] + bit_tables + payloads)


def decode_records_py(words: np.ndarray, nbits: int):
    """Decode one row's record stream into [(kind, ...), ...] — the
    pure-Python engine fallback and the wire-format test oracle.
    kinds: ("dec", ctx, b) ("run", ctx, cnt) ("byp", [bits]) ("trm", b).
    """
    out = []
    pos = 0

    def rd(n):
        nonlocal pos
        v = 0
        for _ in range(n):
            w = int(words[pos >> 5])
            v = (v << 1) | ((w >> (31 - (pos & 31))) & 1)
            pos += 1
        return v

    while pos < nbits:
        if rd(1) == 0:
            out.append(("dec", rd(9), rd(1)))
        elif rd(1) == 0:
            out.append(("run", rd(9), rd(4)))
        elif rd(1) == 0:
            n = rd(4)
            out.append(("byp", [rd(1) for _ in range(n)]))
        else:
            out.append(("trm", rd(1)))
    assert pos == nbits, "record stream over-ran its bit count"
    return out
