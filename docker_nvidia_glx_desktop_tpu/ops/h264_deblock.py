"""H.264 in-loop deblocking filter (spec 8.7) under slice-per-row.

The reference's NVENC applies the normative loop filter; rounds 1-2 of
this rebuild disabled it per slice header (legal, visibly blockier at
streaming QPs).  This module implements it TPU-first:

- **Slice structure does the parallelization**: with
  ``disable_deblocking_filter_idc=2`` the filter must not cross slice
  boundaries, and our slices ARE the MB rows — so only vertical edges
  (x=0,4,8,12 of each MB) and the INTERNAL horizontal edges (y=4,8,12)
  are filtered.  Every MB row is independent; the only sequencing is the
  spec's left-to-right MB order inside a row (MB n's x=0 edge reads and
  REWRITES the last columns of MB n-1 after n-1 finished), which maps to
  the same 120-step `lax.scan` the intra encoder uses, vectorized over
  all rows.
- **Filter tables** (Table 8-16/8-17 alpha/beta/tc0 — ~160 bytes of
  constants not derivable from formulas) are recovered STRUCTURALLY from
  the system libx264 .rodata, the same oracle pattern as the VP8
  probability tables (bitstream/vp8_tables.py): monotone 52-entry
  sequences with known heads/tails, cross-checked between two embedded
  copies.  Correctness is then pinned end-to-end: the conformant decoder
  (FFmpeg via cv2) applies ITS tables to our streams and must match our
  filtered reconstruction — wrong values desynchronize immediately and
  compound through every P frame.

The numpy reference (`deblock_frame_ref`) implements the spec order
literally; the device scan is byte-identity-tested against it.
"""

from __future__ import annotations

import functools

import numpy as np

__all__ = ["load_tables", "deblock_frame_ref"]

_LIBX264 = (
    "/lib/x86_64-linux-gnu/libx264.so.164",
    "/usr/lib/x86_64-linux-gnu/libx264.so.164",
)


def _candidate_paths():
    from ..utils.librecovery import candidate_paths
    return candidate_paths(fixed=_LIBX264, stems=("x264",))


@functools.lru_cache(maxsize=1)
def load_tables():
    """(alpha (52,), beta (52,), tc0 (52, 3)) int32, recovered + validated."""
    data = None
    for path in _candidate_paths():
        try:
            data = np.frombuffer(open(path, "rb").read(), np.uint8)
            break
        except OSError:
            continue
    if data is None:
        raise RuntimeError(
            "libx264 not found: deblock tables unavailable (install "
            "libx264 / ffmpeg; see deploy/Dockerfile)")
    raw = data.tobytes()

    # alpha: 52 entries, 16 leading zeros, nondecreasing, ends 255,255
    # with 226 before — a unique structural signature.
    alpha = None
    i = -1
    while True:
        i = raw.find(bytes([203, 226, 255, 255]), i + 1)
        if i < 0:
            break
        w = data[i + 4 - 52:i + 4].astype(np.int64)
        if (w[:16] == 0).all() and (np.diff(w) >= 0).all() and w[16] > 0:
            if alpha is not None and not (alpha == w).all():
                raise RuntimeError("ambiguous alpha recovery")
            alpha = w
    # beta: ends ...17,17,18,18 then x264's QP-extension padding of 18s;
    # anchor on the last strictly-increasing step (17,18) and require the
    # 36-entry nonzero tail plus 16 leading zeros.
    beta = None
    i = -1
    while True:
        i = raw.find(bytes([16, 17, 17, 18, 18, 18]), i + 1)
        if i < 0:
            break
        w = data[i + 5 - 52:i + 5].astype(np.int64)
        if (w[:16] == 0).all() and (np.diff(w) >= 0).all() and w[16] == 2:
            if beta is not None and not (beta == w).all():
                raise RuntimeError("ambiguous beta recovery")
            beta = w
    # tc0: stored as rows (255, bs1, bs2, bs3); the core's indexA=51 row
    # is the FIRST (255,13,17,25) (later copies are QP-extension padding).
    tc0 = None
    i = raw.find(bytes([255, 13, 17, 25]))
    if i >= 0:
        rows = data[i + 4 - 52 * 4:i + 4].reshape(52, 4).astype(np.int64)
        good = ((rows[:, 0] == 255).all()
                and (rows[0, 1:] == 0).all()
                and (np.diff(rows[:, 1:], axis=0) >= 0).all()
                and tuple(rows[51, 1:]) == (13, 17, 25))
        if good:
            tc0 = rows[:, 1:]
    if alpha is None or beta is None or tc0 is None:
        raise RuntimeError("deblock table recovery failed "
                           f"(alpha={alpha is not None} "
                           f"beta={beta is not None} tc0={tc0 is not None})")
    return (alpha.astype(np.int32), beta.astype(np.int32),
            tc0.astype(np.int32))


def _clip3(lo, hi, x):
    return np.minimum(hi, np.maximum(lo, x))


# ---------------------------------------------------------------------------
# Device implementation: one lax.scan over MB columns (the spec's
# left-to-right order inside each row; all MB rows vectorized), edges
# filtered as fully-vectorized line bundles.
# ---------------------------------------------------------------------------

def _filter_lines(p, q, bs, alpha, beta, tc0_row, chroma: bool):
    """Vectorized spec 8.7.2.3/8.7.2.4 over line bundles.

    p, q: (..., 4) int32 with index 0 nearest the edge; bs: (...,) int32.
    alpha/beta ints, tc0_row (3,).  Returns (p_new, q_new) with only
    indices 0..2 possibly changed."""
    import jax.numpy as jnp

    p0, p1, p2, p3 = (p[..., i] for i in range(4))
    q0, q1, q2, q3 = (q[..., i] for i in range(4))
    fil = ((jnp.abs(p0 - q0) < alpha) & (jnp.abs(p1 - p0) < beta)
           & (jnp.abs(q1 - q0) < beta) & (bs > 0))
    ap = jnp.abs(p2 - p0) < beta
    aq = jnp.abs(q2 - q0) < beta

    # --- bS < 4 normal filter ---
    t0 = jnp.where(bs <= 1, int(tc0_row[0]),
                   jnp.where(bs == 2, int(tc0_row[1]), int(tc0_row[2])))
    tc = t0 + (1 if chroma
               else 0) + (0 if chroma
                          else ap.astype(jnp.int32) + aq.astype(jnp.int32))
    delta = jnp.clip(((q0 - p0) * 4 + (p1 - q1) + 4) >> 3, -tc, tc)
    n_p0 = jnp.clip(p0 + delta, 0, 255)
    n_q0 = jnp.clip(q0 - delta, 0, 255)
    if chroma:
        n_p1, n_q1, n_p2, n_q2 = p1, q1, p2, q2
    else:
        dp1 = jnp.clip((p2 + ((p0 + q0 + 1) >> 1) - 2 * p1) >> 1, -t0, t0)
        dq1 = jnp.clip((q2 + ((p0 + q0 + 1) >> 1) - 2 * q1) >> 1, -t0, t0)
        n_p1 = jnp.where(ap, p1 + dp1, p1)
        n_q1 = jnp.where(aq, q1 + dq1, q1)
        n_p2, n_q2 = p2, q2

    # --- bS == 4 strong filter ---
    strong = jnp.abs(p0 - q0) < ((alpha >> 2) + 2)
    s_p0w = (2 * p1 + p0 + q1 + 2) >> 2
    s_q0w = (2 * q1 + q0 + p1 + 2) >> 2
    if chroma:
        s_p0, s_p1, s_p2 = s_p0w, p1, p2
        s_q0, s_q1, s_q2 = s_q0w, q1, q2
    else:
        use_p = strong & ap
        use_q = strong & aq
        s_p0 = jnp.where(use_p,
                         (p2 + 2 * p1 + 2 * p0 + 2 * q0 + q1 + 4) >> 3,
                         s_p0w)
        s_p1 = jnp.where(use_p, (p2 + p1 + p0 + q0 + 2) >> 2, p1)
        s_p2 = jnp.where(use_p,
                         (2 * p3 + 3 * p2 + p1 + p0 + q0 + 4) >> 3, p2)
        s_q0 = jnp.where(use_q,
                         (q2 + 2 * q1 + 2 * q0 + 2 * p0 + p1 + 4) >> 3,
                         s_q0w)
        s_q1 = jnp.where(use_q, (q2 + q1 + q0 + p0 + 2) >> 2, q1)
        s_q2 = jnp.where(use_q,
                         (2 * q3 + 3 * q2 + q1 + q0 + p0 + 4) >> 3, q2)

    bs4 = bs == 4
    o_p0 = jnp.where(bs4, s_p0, n_p0)
    o_p1 = jnp.where(bs4, s_p1, n_p1)
    o_p2 = jnp.where(bs4, s_p2, n_p2)
    o_q0 = jnp.where(bs4, s_q0, n_q0)
    o_q1 = jnp.where(bs4, s_q1, n_q1)
    o_q2 = jnp.where(bs4, s_q2, n_q2)

    sel = lambda n, o: jnp.where(fil, n, o)
    import jax.numpy as _j
    p_new = _j.stack([sel(o_p0, p0), sel(o_p1, p1), sel(o_p2, p2), p3],
                     axis=-1)
    q_new = _j.stack([sel(o_q0, q0), sel(o_q1, q1), sel(o_q2, q2), q3],
                     axis=-1)
    return p_new, q_new


def _edge_v_mb(mb, x, bs, alpha, beta, tc0, chroma):
    """Filter the vertical edge at column ``x`` of (..., n, W) in place."""
    import jax.numpy as jnp

    p = jnp.stack([mb[..., x - 1 - k] for k in range(4)], axis=-1)
    q = jnp.stack([mb[..., x + k] for k in range(4)], axis=-1)
    p, q = _filter_lines(p, q, bs, alpha, beta, tc0, chroma)
    for k in range(3):
        mb = mb.at[..., x - 1 - k].set(p[..., k])
        mb = mb.at[..., x + k].set(q[..., k])
    return mb


def _edge_h_mb(mb, y, bs, alpha, beta, tc0, chroma):
    """Filter the horizontal edge at row ``y`` of (..., H, W) in place."""
    import jax.numpy as jnp

    p = jnp.stack([mb[..., y - 1 - k, :] for k in range(4)], axis=-1)
    q = jnp.stack([mb[..., y + k, :] for k in range(4)], axis=-1)
    p, q = _filter_lines(p, q, bs, alpha, beta, tc0, chroma)
    for k in range(3):
        mb = mb.at[..., y - 1 - k, :].set(p[..., k])
        mb = mb.at[..., y + k, :].set(q[..., k])
    return mb


import jax as _jax


@functools.partial(_jax.jit, static_argnames=("qp", "_group"))
def deblock_frame(y, cb, cr, qp: int, nnz_blk=None, mv=None,
                  _group: int = 0):
    """Device loop filter for one frame (slice-per-row, idc=2 edges).

    y (H, W), cb/cr (H/2, W/2) uint8 recon planes.  Intra frames pass
    nnz_blk=None (static bS: 4 at MB edges, 3 internal); P frames pass
    nnz_blk (R, C, 4, 4) bool and mv (R, C, 2) quarter-pel.  Returns
    filtered uint8 planes.  Byte-identical to :func:`deblock_frame_ref`
    (tested).

    ``_group``: MB columns per scan step (0 = auto).  The left-to-right
    MB order is a true sample dependency — MB n's x=0 edge rewrites MB
    n-1's last columns AFTER n-1 finished — so the dependency chain is
    irreducible, but each ``lax.scan`` step carries fixed overhead
    (carry shuffling + fusion dispatch), and at 4K the two 120+-step
    column scans cost ~8.7 ms (BENCH_r05).  The wavefront restructure
    runs GROUPS of columns per step with the in-group chain statically
    unrolled: the op sequence is identical (byte-exact, tested against
    ``_group=1`` and the numpy reference), the fusions are group-times
    wider, and the scan shrinks to nc/group steps."""
    import jax
    import jax.numpy as jnp

    from . import quant as _q

    alpha_t, beta_t, tc0_t = load_tables()
    qp_c = _q.chroma_qp(qp)
    a_l, b_l, t_l = int(alpha_t[qp]), int(beta_t[qp]), tc0_t[qp]
    a_c, b_c, t_c = int(alpha_t[qp_c]), int(beta_t[qp_c]), tc0_t[qp_c]
    H, W = y.shape
    nr, nc = H // 16, W // 16
    intra = nnz_blk is None

    if not intra:
        nnz16y = jnp.repeat(nnz_blk.astype(jnp.int32), 4, axis=2)
        # (R, C, 16 lines, 4 bx) — per-line nnz along vertical edges
        bs_v_int = jnp.stack(
            [(nnz16y[:, :, :, bx - 1] | nnz16y[:, :, :, bx]) * 2
             for bx in (1, 2, 3)], axis=2)                 # (R, C, 3, 16)
        left_nnz = jnp.concatenate(
            [jnp.zeros((nr, 1, 16), jnp.int32), nnz16y[:, :-1, :, 3]],
            axis=1)
        mvd = jnp.concatenate(
            [jnp.zeros((nr, 1), bool),
             (jnp.abs(mv[:, 1:] - mv[:, :-1]) >= 4).any(-1)], axis=1)
        bs_mb0 = jnp.where((left_nnz | nnz16y[:, :, :, 0]) > 0, 2,
                           jnp.where(mvd[:, :, None], 1, 0))
        bs_mb0 = bs_mb0.at[:, 0].set(0)
        nnz16x = jnp.repeat(nnz_blk.astype(jnp.int32), 4, axis=3)
        bs_h_int = jnp.stack(
            [(nnz16x[:, :, by - 1] | nnz16x[:, :, by]) * 2
             for by in (1, 2, 3)], axis=2)                 # (R, C, 3, 16)
        # scan-major layouts (C leading)
        bs_v_int = jnp.moveaxis(bs_v_int, 1, 0)            # (C, R, 3, 16)
        bs_mb0 = jnp.moveaxis(bs_mb0, 1, 0)                # (C, R, 16)
        bs_h_int = jnp.moveaxis(bs_h_int, 1, 0)

    # MB-tiled planes, scan axis (MB column) leading
    ymbs = jnp.moveaxis(
        y.astype(jnp.int32).reshape(nr, 16, nc, 16).transpose(0, 2, 1, 3),
        1, 0)                                              # (C, R, 16, 16)
    cbm = jnp.moveaxis(
        cb.astype(jnp.int32).reshape(nr, 8, nc, 8).transpose(0, 2, 1, 3),
        1, 0)
    crm = jnp.moveaxis(
        cr.astype(jnp.int32).reshape(nr, 8, nc, 8).transpose(0, 2, 1, 3),
        1, 0)

    # Auto group: the wavefront amortizes the PER-STEP cost of a scan
    # iteration (fusion dispatch + carry shuffling), which is what the
    # ~8.7 ms column scans at 4K are made of on an accelerator backend.
    # The CPU backend has no such per-step cost and measured the wider
    # steps 1.5x SLOWER (BENCH_r06 profile), so auto keeps the column
    # scan there; pass ``_group`` explicitly to override either way.
    if _group:
        group = _group
    elif _jax.default_backend() == "cpu":
        group = 1
    else:
        group = next(g for g in (8, 6, 5, 4, 3, 2, 1) if nc % g == 0)

    def col_step(carry, xs):
        yl, cbl, crl = carry            # left MB last-4 columns, post-H
        if intra:
            ymb, cbmb, crmb, idx = xs
            bs0 = jnp.full((nr, 16), 4, jnp.int32)
            bsv = [jnp.full((nr, 16), 3, jnp.int32)] * 3
            bsh = [jnp.full((nr, 16), 3, jnp.int32)] * 3
        else:
            ymb, cbmb, crmb, bsv3, bs0, bsh3, idx = xs
            bsv = [bsv3[:, e] for e in range(3)]
            bsh = [bsh3[:, e] for e in range(3)]
        has_left = idx > 0
        bs0 = jnp.where(has_left, bs0, 0)

        # --- luma: x=0 MB edge spans the carry (p) and this MB (q);
        # the H pass covers only THIS MB's 16 columns (the carry's H
        # edges were filtered in the previous step) ---
        wide = jnp.concatenate([yl, ymb], axis=-1)         # (R, 16, 20)
        wide = _edge_v_mb(wide, 4, bs0, a_l, b_l, t_l, False)
        for e, x in enumerate((4, 8, 12)):
            wide = _edge_v_mb(wide, 4 + x, bsv[e], a_l, b_l, t_l, False)
        left_fin = wide[..., :4]        # left MB cols 12..15, FINAL
        own = wide[..., 4:]
        for e, yy_ in enumerate((4, 8, 12)):
            own = _edge_h_mb(own, yy_, bsh[e], a_l, b_l, t_l, False)

        # --- chroma: MB edge + internal x=4 (luma x=8), h y=4 (luma 8) --
        def chroma_mb(mbp, left):
            w2 = jnp.concatenate([left, mbp], axis=-1)     # (R, 8, 12)
            w2 = _edge_v_mb(w2, 4, bs0[:, 0::2], a_c, b_c, t_c, True)
            w2 = _edge_v_mb(w2, 8, bsv[1][:, 0::2], a_c, b_c, t_c, True)
            lf, ownp = w2[..., :4], w2[..., 4:]
            ownp = _edge_h_mb(ownp, 4, bsh[1][:, 0::2], a_c, b_c, t_c,
                              True)
            return lf, ownp

        cbl_fin, cb_own = chroma_mb(cbmb, cbl)
        crl_fin, cr_own = chroma_mb(crmb, crl)

        carry = (own[..., -4:], cb_own[..., -4:], cr_own[..., -4:])
        out = (left_fin[..., 1:], own[..., :13],
               cbl_fin[..., 2:], cb_own[..., :6],
               crl_fin[..., 2:], cr_own[..., :6])
        return carry, out

    def step(carry, xs_g):
        # one wavefront step: ``group`` columns chained in-body (the
        # same per-column op sequence col_step always ran, unrolled)
        outs = []
        for g in range(group):
            carry, out = col_step(carry, tuple(x[g] for x in xs_g))
            outs.append(out)
        return carry, tuple(jnp.stack(parts, 0)
                            for parts in zip(*outs))

    init = (jnp.zeros((nr, 16, 4), jnp.int32),
            jnp.zeros((nr, 8, 4), jnp.int32),
            jnp.zeros((nr, 8, 4), jnp.int32))
    if intra:
        xs = (ymbs, cbm, crm, jnp.arange(nc, dtype=jnp.int32))
    else:
        xs = (ymbs, cbm, crm, bs_v_int, bs_mb0, bs_h_int,
              jnp.arange(nc, dtype=jnp.int32))
    xs = tuple(x.reshape((nc // group, group) + x.shape[1:]) for x in xs)
    carry, outs = jax.lax.scan(step, init, xs)
    outs = tuple(o.reshape((nc,) + o.shape[2:]) for o in outs)
    lf3, own13, cblf, cbo6, crlf, cro6 = outs

    def assemble(own_first, later_last, tailc, sub):
        """MB c's leading columns from step c, trailing columns from
        step c+1 (which finalized them via its x=0 edge)."""
        last = jnp.concatenate([later_last[1:], tailc[None]], axis=0)
        mbs = jnp.concatenate([own_first, last], axis=-1)   # (C,R,s,s)
        full = jnp.moveaxis(mbs, 0, 1)                      # (R,C,s,s)
        return full.transpose(0, 2, 1, 3).reshape(H // sub, W // sub)

    y_out = assemble(own13, lf3, carry[0][..., 1:], 1)
    cb_out = assemble(cbo6, cblf, carry[1][..., 2:], 2)
    cr_out = assemble(cro6, crlf, carry[2][..., 2:], 2)
    clip = lambda p: jnp.clip(p, 0, 255).astype(jnp.uint8)
    return clip(y_out), clip(cb_out), clip(cr_out)


def _filter_line(p, q, bs, alpha, beta, tc0_row, chroma):
    """Filter ONE edge line (spec 8.7.2.3/8.7.2.4), in place on numpy
    int32 vectors p[0..3] (p0 nearest the edge) and q[0..3]."""
    if bs == 0:
        return
    p0, p1, p2, p3 = p[0], p[1], p[2], p[3]
    q0, q1, q2, q3 = q[0], q[1], q[2], q[3]
    if not (abs(int(p0 - q0)) < alpha and abs(int(p1 - p0)) < beta
            and abs(int(q1 - q0)) < beta):
        return
    if bs < 4:
        tc0 = int(tc0_row[bs - 1])
        ap = abs(int(p2 - p0)) < beta
        aq = abs(int(q2 - q0)) < beta
        if chroma:
            tc = tc0 + 1
        else:
            tc = tc0 + int(ap) + int(aq)
        delta = _clip3(-tc, tc, ((q0 - p0) * 4 + (p1 - q1) + 4) >> 3)
        p[0] = _clip3(0, 255, p0 + delta)
        q[0] = _clip3(0, 255, q0 - delta)
        if not chroma:
            if ap:
                p[1] = p1 + _clip3(-tc0, tc0,
                                   (p2 + ((p0 + q0 + 1) >> 1) - 2 * p1) >> 1)
            if aq:
                q[1] = q1 + _clip3(-tc0, tc0,
                                   (q2 + ((p0 + q0 + 1) >> 1) - 2 * q1) >> 1)
    else:                                   # bS == 4
        strong = abs(int(p0 - q0)) < (alpha >> 2) + 2
        ap = abs(int(p2 - p0)) < beta
        aq = abs(int(q2 - q0)) < beta
        if not chroma and strong and ap:
            p[0] = (p2 + 2 * p1 + 2 * p0 + 2 * q0 + q1 + 4) >> 3
            p[1] = (p2 + p1 + p0 + q0 + 2) >> 2
            p[2] = (2 * p3 + 3 * p2 + p1 + p0 + q0 + 4) >> 3
        else:
            p[0] = (2 * p1 + p0 + q1 + 2) >> 2
        if not chroma and strong and aq:
            q[0] = (q2 + 2 * q1 + 2 * q0 + 2 * p0 + p1 + 4) >> 3
            q[1] = (q2 + q1 + q0 + p0 + 2) >> 2
            q[2] = (2 * q3 + 3 * q2 + q1 + q0 + p0 + 4) >> 3
        else:
            q[0] = (2 * q1 + q0 + p1 + 2) >> 2


def _edge_v(plane, y0, x, n, bs_per_line, alpha, beta, tc0, chroma):
    """Vertical edge at column x: lines y0..y0+n-1."""
    for j in range(n):
        bs = int(bs_per_line[j])
        if bs == 0:
            continue
        row = plane[y0 + j]
        p = np.array([row[x - 1], row[x - 2], row[x - 3], row[x - 4]],
                     np.int32)
        q = np.array([row[x], row[x + 1], row[x + 2], row[x + 3]], np.int32)
        _filter_line(p, q, bs, alpha, beta, tc0, chroma)
        row[x - 3:x] = p[2::-1]
        row[x:x + 3] = q[:3]


def _edge_h(plane, x0, y, n, bs_per_line, alpha, beta, tc0, chroma):
    """Horizontal edge at row y: lines x0..x0+n-1."""
    for j in range(n):
        bs = int(bs_per_line[j])
        if bs == 0:
            continue
        col = plane[:, x0 + j]
        p = np.array([col[y - 1], col[y - 2], col[y - 3], col[y - 4]],
                     np.int32)
        q = np.array([col[y], col[y + 1], col[y + 2], col[y + 3]], np.int32)
        _filter_line(p, q, bs, alpha, beta, tc0, chroma)
        col[y - 3:y] = p[2::-1]
        col[y:y + 3] = q[:3]


def intra_bs(nr: int, nc: int):
    """bS grids for an all-intra frame under slice-per-row: vertical MB
    edges (x=0) are 4, internal edges 3; returns (bs_v (R,C,4,16),
    bs_h (R,C,3,16)) — per edge, per line."""
    bs_v = np.zeros((nr, nc, 4, 16), np.int32)
    bs_v[:, :, 1:, :] = 3
    bs_v[:, 1:, 0, :] = 4            # MB boundary (first MB: no left edge)
    bs_h = np.full((nr, nc, 3, 16), 3, np.int32)
    return bs_v, bs_h


def p_bs(nnz_blk: np.ndarray, mv: np.ndarray):
    """bS grids for a P frame (no intra MBs, one MV per MB).

    nnz_blk: (R, C, 4, 4) bool — 4x4 block has coded coefficients
    (raster [by][bx]); mv: (R, C, 2) quarter-pel.  Internal edges: 2 if
    either side has coefficients else 0 (one MV per MB -> no internal mv
    term); the x=0 MB edge adds bS=1 when the MVs differ by >= 4 quarter
    units on either axis."""
    nr, nc = nnz_blk.shape[:2]
    bs_v = np.zeros((nr, nc, 4, 16), np.int32)
    bs_h = np.zeros((nr, nc, 3, 16), np.int32)
    nnz16 = np.repeat(nnz_blk, 4, axis=2)          # (R, C, 16, 4) by-lines
    for e, bx in enumerate((1, 2, 3)):             # internal vertical
        two = (nnz16[:, :, :, bx - 1] | nnz16[:, :, :, bx]) * 2
        bs_v[:, :, e + 1, :] = two
    left_nnz = np.zeros((nr, nc, 16), bool)
    left_nnz[:, 1:] = nnz16[:, :-1, :, 3]
    mvd = np.zeros((nr, nc), bool)
    mvd[:, 1:] = (np.abs(mv[:, 1:] - mv[:, :-1]) >= 4).any(axis=-1)
    edge0 = np.where(left_nnz | nnz16[:, :, :, 0], 2,
                     np.where(mvd[:, :, None], 1, 0))
    bs_v[:, :, 0, :] = edge0
    bs_v[:, 0, 0, :] = 0                           # no left MB
    nnzx = np.repeat(nnz_blk, 4, axis=3)           # (R, C, 4, 16) bx-lines
    for e, by in enumerate((1, 2, 3)):             # internal horizontal
        bs_h[:, :, e, :] = (nnzx[:, :, by - 1] | nnzx[:, :, by]) * 2
    return bs_v, bs_h


def deblock_frame_ref(y, cb, cr, qp: int, qp_c: int, bs_v, bs_h):
    """Numpy reference: filter one frame in the spec's MB order.

    y (H, W), cb/cr (H/2, W/2) uint8; bs_v (R, C, 4, 16) vertical-edge
    bS per line, bs_h (R, C, 3, 16) internal horizontal edges (y=4,8,12).
    Returns filtered copies."""
    alpha_t, beta_t, tc0_t = load_tables()
    a_l, b_l, t_l = (int(alpha_t[qp]), int(beta_t[qp]), tc0_t[qp])
    a_c, b_c, t_c = (int(alpha_t[qp_c]), int(beta_t[qp_c]), tc0_t[qp_c])
    y = y.astype(np.int32).copy()
    cb = cb.astype(np.int32).copy()
    cr = cr.astype(np.int32).copy()
    nr, nc = bs_v.shape[:2]
    for r in range(nr):
        for c in range(nc):
            my, mx = r * 16, c * 16
            # vertical luma edges x=0,4,8,12; chroma x=0,4 (from luma 0,8)
            for e, dx in enumerate((0, 4, 8, 12)):
                if c == 0 and dx == 0:
                    continue
                _edge_v(y, my, mx + dx, 16, bs_v[r, c, e], a_l, b_l, t_l,
                        False)
            for plane in (cb, cr):
                if c > 0:
                    _edge_v(plane, my // 2, mx // 2, 8,
                            bs_v[r, c, 0, 0::2], a_c, b_c, t_c, True)
                _edge_v(plane, my // 2, mx // 2 + 4, 8,
                        bs_v[r, c, 2, 0::2], a_c, b_c, t_c, True)
            # horizontal edges y=4,8,12 (y=0 is the slice boundary);
            # chroma y=4 (from luma y=8)
            for e, dy in enumerate((4, 8, 12)):
                _edge_h(y, mx, my + dy, 16, bs_h[r, c, e], a_l, b_l, t_l,
                        False)
            for plane in (cb, cr):
                _edge_h(plane, mx // 2, my // 2 + 4, 8,
                        bs_h[r, c, 1, 0::2], a_c, b_c, t_c, True)
    clip = lambda p: np.clip(p, 0, 255).astype(np.uint8)
    return clip(y), clip(cb), clip(cr)
