"""H.264 intra (I_16x16) transform/quant/recon stage on device.

TPU-first design (SURVEY.md §2.3 "intra-frame parallelism"): the reference
encodes inside NVENC silicon with wavefront MB pipelines; we instead make
each macroblock **row** its own slice, which legalizes full row parallelism
— intra prediction then only ever references the MB to the left, so the
frame is a `vmap` over rows crossed with a 120-step `lax.scan` along the
row (1080p).  Each scan step processes one MB column across all rows: 68
MBs of 4x4 integer DCTs, Hadamard DC, quant, and normative reconstruction,
all batched int32 VPU work that XLA fuses into a handful of kernels.

Prediction uses DC mode only (Intra16x16PredMode=2, chroma DC mode 0):
with the top row in another slice, the only available reference is the
left MB's reconstructed right column, carried through the scan.  The
reconstruction here is bit-exact against conformant decoders (verified in
tests by decoding our stream with FFmpeg-backed cv2).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import color, quant
from .dct import fdct4x4 as _fwd4x4
from .dct import hadamard2x2 as _had2
from .dct import hadamard4x4 as _had4
from .dct import idct4x4 as _inv4x4

# Zigzag scan for 4x4 blocks (raster index at each scan position).
ZIGZAG4 = np.array([0, 1, 4, 8, 5, 2, 3, 6, 9, 12, 13, 10, 7, 11, 14, 15],
                   dtype=np.int32)

# luma4x4BlkIdx -> (bx, by) in 4-sample units (spec §6.4.3).
LUMA_BLOCK_ORDER = np.array(
    [(0, 0), (1, 0), (0, 1), (1, 1),
     (2, 0), (3, 0), (2, 1), (3, 1),
     (0, 2), (1, 2), (0, 3), (1, 3),
     (2, 2), (3, 2), (2, 3), (3, 3)], dtype=np.int32)


def _blocks(mb, n):
    """(..., 16|8, 16|8) MB -> (..., n/4?, ...) -> (..., by, bx, 4, 4)."""
    s = mb.shape
    b = mb.reshape(s[:-2] + (n, 4, n, 4))
    return jnp.moveaxis(b, -2, -3)  # (..., by, bx, 4, 4)


def _unblocks(b):
    """Inverse of :func:`_blocks`."""
    s = b.shape
    m = jnp.moveaxis(b, -3, -2)  # (..., by, 4, bx, 4)
    return m.reshape(s[:-4] + (s[-4] * 4, s[-3] * 4))


H_PRED_MARGIN = 16     # SAD advantage H must show over DC (tie-break bits)


def _luma_step(ymb, left_col, has_left, qp, allow_h: bool = False):
    """One MB column of luma across all rows.

    ymb: (R, 16, 16) int32; left_col: (R, 16) recon right column of left MB.
    Returns (ac_levels (R,4,4,4,4), dc_levels (R,4,4), recon (R,16,16),
    mode (R,) Intra16x16PredMode — 2 = DC, 1 = Horizontal).

    With ``allow_h`` the per-MB mode decision compares prediction SAD: H
    copies the left MB's reconstructed right column across each row (the
    only directional mode available under slice-per-row, where the MB
    above is in another slice), which nails content constant along x —
    window chrome, toolbars, text rows.
    """
    psum = (jnp.sum(left_col, axis=-1) + 8) >> 4
    pred_dc = jnp.where(has_left, psum, 128)[:, None, None]   # (R, 1, 1)
    if allow_h:
        pred_h = jnp.broadcast_to(left_col[:, :, None], left_col.shape + (16,))
        cost_dc = jnp.abs(ymb - pred_dc).sum(axis=(1, 2))
        cost_h = jnp.abs(ymb - pred_h).sum(axis=(1, 2))
        use_h = has_left & (cost_h + H_PRED_MARGIN < cost_dc)
        pred = jnp.where(use_h[:, None, None], pred_h, pred_dc)
        mode = jnp.where(use_h, 1, 2).astype(jnp.int32)
    else:
        pred = pred_dc
        mode = jnp.full(ymb.shape[:1], 2, jnp.int32)
    res = ymb - pred
    w = _fwd4x4(_blocks(res, 4))                      # (R, by, bx, 4, 4)
    dc = w[..., 0, 0]                                 # (R, by, bx)
    ac = quant.h264_quantize_4x4(w, qp, intra=True)
    ac = ac.at[..., 0, 0].set(0)

    wd2 = _had4(dc)
    wd = jnp.sign(wd2) * (jnp.abs(wd2) >> 1)          # /2, truncate to zero
    dcl = quant.h264_quantize_luma_dc(wd, qp)

    # normative reconstruction
    fd = _had4(dcl)
    dcy = quant.h264_dequantize_luma_dc(fd, qp)
    wr = quant.h264_dequantize_4x4(ac, qp)
    wr = wr.at[..., 0, 0].set(dcy)
    resr = _inv4x4(wr)
    recon = jnp.clip(pred + _unblocks(resr), 0, 255)
    return ac, dcl, recon, mode


def _chroma_step(cmb, left_col, has_left, qp_c):
    """One MB column of one chroma plane across all rows.

    cmb: (R, 8, 8); left_col: (R, 8).  DC prediction per 4x4 quadrant: with
    the top slice boundary, quadrant (bx, by) predicts from left rows
    4*by..4*by+3 (spec §8.3.4.1 fallbacks), or 128 with no left MB.
    """
    lsum = left_col.reshape(-1, 2, 4).sum(axis=-1)    # (R, by)
    pq = (lsum + 2) >> 2                              # (R, by)
    pred_q = jnp.where(has_left, pq[:, :, None], 128)  # (R, by, bx)
    res = _blocks(cmb, 2) - pred_q[..., None, None]
    w = _fwd4x4(res)
    dc = w[..., 0, 0]                                 # (R, 2, 2)
    ac = quant.h264_quantize_4x4(w, qp_c, intra=True)
    ac = ac.at[..., 0, 0].set(0)
    wd = _had2(dc)
    dcl = quant.h264_quantize_chroma_dc(wd, qp_c)

    fd = _had2(dcl)
    dcc = quant.h264_dequantize_chroma_dc(fd, qp_c)
    wr = quant.h264_dequantize_4x4(ac, qp_c)
    wr = wr.at[..., 0, 0].set(dcc)
    resr = _inv4x4(wr)
    recon = jnp.clip(pred_q[..., None, None] + resr, 0, 255)
    return ac, dcl, _unblocks(recon)


@functools.partial(jax.jit,
                   static_argnames=("pad_h", "pad_w", "qp", "i16_modes"))
def encode_intra_frame(rgb, pad_h: int, pad_w: int, qp: int,
                       i16_modes: str = "auto"):
    """Full device stage: RGB frame -> quantized level tensors + recon.

    Returns a dict of int32/uint8 arrays (see keys below); shapes use
    R = pad_h//16 MB rows and C = pad_w//16 MB columns.
    """
    h, w = rgb.shape[0], rgb.shape[1]
    rgb_p = jnp.pad(jnp.asarray(rgb), ((0, pad_h - h), (0, pad_w - w), (0, 0)),
                    mode="edge")
    yf, cbf, crf = color.rgb_to_yuv420(rgb_p, matrix="video")
    y = jnp.clip(jnp.round(yf), 0, 255).astype(jnp.int32)
    cb = jnp.clip(jnp.round(cbf), 0, 255).astype(jnp.int32)
    cr = jnp.clip(jnp.round(crf), 0, 255).astype(jnp.int32)
    return encode_intra_frame_yuv.__wrapped__(y, cb, cr, qp, i16_modes)


@functools.partial(jax.jit, static_argnames=("qp", "i16_modes"))
def encode_intra_frame_yuv(y, cb, cr, qp: int, i16_modes: str = "auto"):
    """Same device stage from pre-converted YUV 4:2:0 planes (already padded
    to macroblock multiples).  The host-side capture path converts RGB with
    cv2 (BT.601 studio range, matching ops/color "video") and ships 1.5
    bytes/pixel instead of 3 — the host->device link is the hot-path
    bottleneck (SURVEY.md §3.2 PCIe budget)."""
    y = jnp.asarray(y).astype(jnp.int32)
    cb = jnp.asarray(cb).astype(jnp.int32)
    cr = jnp.asarray(cr).astype(jnp.int32)
    pad_h, pad_w = y.shape
    nr, nc = pad_h // 16, pad_w // 16
    qp_c = quant.chroma_qp(qp)

    # (C, R, ...) layouts: scan axis leading.
    ymbs = jnp.moveaxis(
        y.reshape(nr, 16, nc, 16).transpose(0, 2, 1, 3), 1, 0)
    cbmbs = jnp.moveaxis(
        cb.reshape(nr, 8, nc, 8).transpose(0, 2, 1, 3), 1, 0)
    crmbs = jnp.moveaxis(
        cr.reshape(nr, 8, nc, 8).transpose(0, 2, 1, 3), 1, 0)

    def step(carry, xs):
        yl, cbl, crl = carry
        ymb, cbmb, crmb, idx = xs
        has_left = idx > 0
        y_ac, y_dc, y_rec, y_mode = _luma_step(
            ymb, yl, has_left, qp, allow_h=i16_modes == "auto")
        cb_ac, cb_dc, cb_rec = _chroma_step(cbmb, cbl, has_left, qp_c)
        cr_ac, cr_dc, cr_rec = _chroma_step(crmb, crl, has_left, qp_c)
        carry = (y_rec[:, :, 15], cb_rec[:, :, 7], cr_rec[:, :, 7])
        out = (y_ac, y_dc, cb_ac, cb_dc, cr_ac, cr_dc,
               y_rec.astype(jnp.uint8), cb_rec.astype(jnp.uint8),
               cr_rec.astype(jnp.uint8), y_mode)
        return carry, out

    init = (jnp.zeros((nr, 16), jnp.int32), jnp.zeros((nr, 8), jnp.int32),
            jnp.zeros((nr, 8), jnp.int32))
    _, outs = jax.lax.scan(
        step, init, (ymbs, cbmbs, crmbs, jnp.arange(nc, dtype=jnp.int32)))
    (y_ac, y_dc, cb_ac, cb_dc, cr_ac, cr_dc, y_rec, cb_rec, cr_rec,
     y_mode) = outs
    # scan stacked along axis 0 = columns; put rows first: (R, C, ...)
    to_rc = lambda a: jnp.moveaxis(a, 0, 1)

    # --- scan-order reordering (device-side gathers) ---
    zz = jnp.asarray(ZIGZAG4)
    blk = jnp.asarray(LUMA_BLOCK_ORDER)

    y_ac = to_rc(y_ac)                                 # (R, C, by, bx, 4, 4)
    y_acf = y_ac.reshape(nr, nc, 4, 4, 16)[..., zz[1:]]  # zigzag, AC only
    # gather blocks into luma4x4BlkIdx order: index [by, bx] per blkIdx
    y_acf = y_acf[:, :, blk[:, 1], blk[:, 0], :]       # (R, C, 16, 15)

    y_dcf = to_rc(y_dc).reshape(nr, nc, 16)[..., zz]   # (R, C, 16)

    def chroma_fmt(ac, dc):
        ac = to_rc(ac).reshape(nr, nc, 4, 16)[..., zz[1:]]  # blocks raster
        dc = to_rc(dc).reshape(nr, nc, 4)
        return ac, dc

    cb_acf, cb_dcf = chroma_fmt(cb_ac, cb_dc)
    cr_acf, cr_dcf = chroma_fmt(cr_ac, cr_dc)

    # recon planes reassembled for tests / PSNR
    y_full = to_rc(y_rec).transpose(0, 2, 1, 3).reshape(pad_h, pad_w)
    cb_full = to_rc(cb_rec).transpose(0, 2, 1, 3).reshape(pad_h // 2, pad_w // 2)
    cr_full = to_rc(cr_rec).transpose(0, 2, 1, 3).reshape(pad_h // 2, pad_w // 2)

    return {
        "luma_dc": y_dcf,        # (R, C, 16) zigzag
        "luma_ac": y_acf,        # (R, C, 16 blkIdx, 15) zigzag
        "cb_dc": cb_dcf,         # (R, C, 4) raster
        "cb_ac": cb_acf,         # (R, C, 4 raster, 15)
        "cr_dc": cr_dcf,
        "cr_ac": cr_acf,
        "pred_mode": to_rc(y_mode),   # (R, C) Intra16x16PredMode (1=H, 2=DC)
        "recon_y": y_full, "recon_cb": cb_full, "recon_cr": cr_full,
    }
