"""H.264 intra (I_16x16) transform/quant/recon stage on device.

TPU-first design (SURVEY.md §2.3 "intra-frame parallelism"): the reference
encodes inside NVENC silicon with wavefront MB pipelines; we instead make
each macroblock **row** its own slice, which legalizes full row parallelism
— intra prediction then only ever references the MB to the left, so the
frame is a `vmap` over rows crossed with a 120-step `lax.scan` along the
row (1080p).  Each scan step processes one MB column across all rows: 68
MBs of 4x4 integer DCTs, Hadamard DC, quant, and normative reconstruction,
all batched int32 VPU work that XLA fuses into a handful of kernels.

Prediction uses DC mode only (Intra16x16PredMode=2, chroma DC mode 0):
with the top row in another slice, the only available reference is the
left MB's reconstructed right column, carried through the scan.  The
reconstruction here is bit-exact against conformant decoders (verified in
tests by decoding our stream with FFmpeg-backed cv2).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import color, quant
from .dct import fdct4x4 as _fwd4x4
from .dct import hadamard2x2 as _had2
from .dct import hadamard4x4 as _had4
from .dct import idct4x4 as _inv4x4

# Zigzag scan for 4x4 blocks (raster index at each scan position).
ZIGZAG4 = np.array([0, 1, 4, 8, 5, 2, 3, 6, 9, 12, 13, 10, 7, 11, 14, 15],
                   dtype=np.int32)

# luma4x4BlkIdx -> (bx, by) in 4-sample units (spec §6.4.3).
LUMA_BLOCK_ORDER = np.array(
    [(0, 0), (1, 0), (0, 1), (1, 1),
     (2, 0), (3, 0), (2, 1), (3, 1),
     (0, 2), (1, 2), (0, 3), (1, 3),
     (2, 2), (3, 2), (2, 3), (3, 3)], dtype=np.int32)


def nnz_blocks_raster(luma_zz):
    """(R, C, 16 blkIdx, 16) zigzag P-luma levels -> (R, C, 4, 4) raster
    nonzero-4x4-block mask (the deblock filter's bS input)."""
    nnz_zz = (luma_zz != 0).any(axis=-1)
    nr, nc = nnz_zz.shape[:2]
    return jnp.zeros((nr, nc, 4, 4), bool).at[
        :, :, LUMA_BLOCK_ORDER[:, 1], LUMA_BLOCK_ORDER[:, 0]].set(nnz_zz)


def _blocks(mb, n):
    """(..., 16|8, 16|8) MB -> (..., n/4?, ...) -> (..., by, bx, 4, 4)."""
    s = mb.shape
    b = mb.reshape(s[:-2] + (n, 4, n, 4))
    return jnp.moveaxis(b, -2, -3)  # (..., by, bx, 4, 4)


def _unblocks(b):
    """Inverse of :func:`_blocks`."""
    s = b.shape
    m = jnp.moveaxis(b, -3, -2)  # (..., by, 4, bx, 4)
    return m.reshape(s[:-4] + (s[-4] * 4, s[-3] * 4))


def _i16_candidate(ymb, pred, qp):
    """Transform/quant/recon one I16 prediction candidate.

    Returns (ac (R,4,4,4,4), dcl (R,4,4), recon (R,16,16), bits (R,))."""
    res = ymb - pred
    w = _fwd4x4(_blocks(res, 4))                      # (R, by, bx, 4, 4)
    dc = w[..., 0, 0]                                 # (R, by, bx)
    ac = quant.h264_quantize_4x4(w, qp, intra=True)
    ac = ac.at[..., 0, 0].set(0)

    wd2 = _had4(dc)
    wd = jnp.sign(wd2) * (jnp.abs(wd2) >> 1)          # /2, truncate to zero
    dcl = quant.h264_quantize_luma_dc(wd, qp)

    # normative reconstruction
    fd = _had4(dcl)
    dcy = quant.h264_dequantize_luma_dc(fd, qp)
    wr = quant.h264_dequantize_4x4(ac, qp)
    wr = wr.at[..., 0, 0].set(dcy)
    resr = _inv4x4(wr)
    recon = jnp.clip(pred + _unblocks(resr), 0, 255)
    bits = (_level_bits_est(ac, (1, 2, 3, 4))
            + _level_bits_est(dcl, (1, 2)))
    return ac, dcl, recon, bits


def _ssd(recon, src, axes):
    d = recon - src
    return (d * d).sum(axis=axes)


def _luma_step(ymb, left_col, has_left, qp, allow_h: bool = False,
               lam=None):
    """One MB column of luma across all rows.

    ymb: (R, 16, 16) int32; left_col: (R, 16) recon right column of left MB.
    Returns (ac_levels (R,4,4,4,4), dc_levels (R,4,4), recon (R,16,16),
    mode (R,) Intra16x16PredMode — 2 = DC, 1 = Horizontal — and the
    chosen candidate's score (R,), the I16-vs-I4 decision input).

    With ``allow_h`` the per-MB decision codes BOTH candidates and keeps
    the better one.  ``lam is None`` (tune=off) scores by estimated
    CAVLC bits alone (a SAD decision measurably mis-picks: structured
    residuals cost fewer bits than their SAD suggests); with ``lam``
    (tune=hq) the score is the Lagrangian SSD + lam * bits, so the
    decision stops ignoring the distortion it is buying.  H copies the
    left MB's reconstructed right column across each row (the only
    directional I16 mode available under slice-per-row), nailing content
    constant along x — window chrome, toolbars, text rows.
    """
    psum = (jnp.sum(left_col, axis=-1) + 8) >> 4
    pred_dc = jnp.where(has_left, psum, 128)[:, None, None]   # (R, 1, 1)
    pred_dc = jnp.broadcast_to(pred_dc, ymb.shape)
    ac, dcl, recon, bits = _i16_candidate(ymb, pred_dc, qp)
    if lam is not None:
        score = _ssd(recon, ymb, (1, 2)).astype(jnp.float32) + lam * bits
    else:
        score = bits
    mode = jnp.full(ymb.shape[:1], 2, jnp.int32)
    if allow_h:
        pred_h = jnp.broadcast_to(left_col[:, :, None], left_col.shape + (16,))
        ac_h, dcl_h, recon_h, bits_h = _i16_candidate(ymb, pred_h, qp)
        if lam is not None:
            score_h = (_ssd(recon_h, ymb, (1, 2)).astype(jnp.float32)
                       + lam * bits_h)
            use_h = has_left & (score_h < score)
            score = jnp.minimum(score,
                                jnp.where(has_left, score_h, jnp.inf))
        else:
            use_h = has_left & (bits_h < score)
            score = jnp.minimum(score,
                                jnp.where(has_left, bits_h, 1 << 30))
        sel = lambda a, b: jnp.where(
            use_h.reshape((-1,) + (1,) * (a.ndim - 1)), a, b)
        ac = sel(ac_h, ac)
        dcl = sel(dcl_h, dcl)
        recon = sel(recon_h, recon)
        mode = jnp.where(use_h, 1, 2).astype(jnp.int32)
    return ac, dcl, recon, mode, score


def _chroma_step(cmb, left_col, has_left, qp_c):
    """One MB column of one chroma plane across all rows.

    cmb: (R, 8, 8); left_col: (R, 8).  DC prediction per 4x4 quadrant: with
    the top slice boundary, quadrant (bx, by) predicts from left rows
    4*by..4*by+3 (spec §8.3.4.1 fallbacks), or 128 with no left MB.
    """
    lsum = left_col.reshape(-1, 2, 4).sum(axis=-1)    # (R, by)
    pq = (lsum + 2) >> 2                              # (R, by)
    pred_q = jnp.where(has_left, pq[:, :, None], 128)  # (R, by, bx)
    res = _blocks(cmb, 2) - pred_q[..., None, None]
    w = _fwd4x4(res)
    dc = w[..., 0, 0]                                 # (R, 2, 2)
    ac = quant.h264_quantize_4x4(w, qp_c, intra=True)
    ac = ac.at[..., 0, 0].set(0)
    wd = _had2(dc)
    dcl = quant.h264_quantize_chroma_dc(wd, qp_c)

    fd = _had2(dcl)
    dcc = quant.h264_dequantize_chroma_dc(fd, qp_c)
    wr = quant.h264_dequantize_4x4(ac, qp_c)
    wr = wr.at[..., 0, 0].set(dcc)
    resr = _inv4x4(wr)
    recon = jnp.clip(pred_q[..., None, None] + resr, 0, 255)
    return ac, dcl, _unblocks(recon)


# ---------------------------------------------------------------------------
# I_NxN (I4x4) luma path — per-4x4-block prediction under slice-per-row
#
# Coding structure chosen for the MB-column scan: the decoder's intra-4x4
# dependency graph inside an MB (left/top/top-right recon) collapses under
# slice-per-row into SEVEN sequential sub-steps per MB, each fully
# vectorized across frame rows:
#
#   - block row by=0 (top row of the slice: no samples above) -> four
#     sequential blocks along bx using the LEFT-family modes
#     {Horizontal, Horizontal-Up, DC(left-only)};
#   - block rows by=1..3 -> one step each, all four bx in parallel, using
#     the VERTICAL-family modes {Vertical, Diagonal-Down-Left,
#     Vertical-Left} whose reference samples come only from the row above
#     (top-right handled by the spec's p[3,-1] substitution where the
#     z-order neighbor is not yet decoded).
#
# Modes outside those sets are never *chosen* (an encoder decision, always
# legal); every emitted mode is computable by a conformant decoder from
# available samples only.  Every decision (block mode, I16 DC-vs-H, and
# the MB-level I16-vs-I4 choice) minimizes estimated CAVLC bits.
# ---------------------------------------------------------------------------

# TR availability per raster (by, bx), by >= 1: the above-right 4x4 block
# must precede the current one in luma4x4BlkIdx (z) coding order.
_BLKIDX_RASTER = np.zeros((4, 4), np.int32)          # [by][bx] -> blkIdx
for _i, (_bx, _by) in enumerate(LUMA_BLOCK_ORDER):
    _BLKIDX_RASTER[_by, _bx] = _i
_TR_AVAIL = np.zeros((4, 4), bool)
for _by in range(1, 4):
    for _bx in range(3):
        _TR_AVAIL[_by, _bx] = (_BLKIDX_RASTER[_by - 1, _bx + 1]
                               < _BLKIDX_RASTER[_by, _bx])
del _i, _bx, _by


def _level_bits_est(lv, axes):
    """Crude CAVLC bit estimate for quantized levels: ~3 bits per nonzero
    plus ~2 per extra magnitude bit.  Used only for the I16-vs-I4
    decision, which must compare *coded size* — a SAD comparison
    systematically overfits toward I4 on noise (sixteen best-of-three
    predictors always beat one, spuriously) while paying ~40+ signaling
    bits per MB for nothing."""
    a = jnp.abs(lv)
    nz = (a > 0).astype(jnp.int32)
    extra = jnp.floor(jnp.log2(jnp.maximum(a, 1).astype(jnp.float32)))
    return (3 * nz + 2 * extra.astype(jnp.int32)).sum(axis=axes)


def _i4_code_block(blk, preds, modes, legal, qp, lam=None):
    """Choose-among-candidates + transform/quant/recon for I4 blocks.

    blk: (..., 4, 4); preds: list of (..., 4, 4); legal: list of (...,)
    bool (or True).  Every candidate is fully coded and the cheapest one
    kept (same rationale as the I16 decision): by estimated CAVLC bits
    alone under tune=off (``lam is None``), by the Lagrangian
    SSD + lam * bits under tune=hq — which costs one extra
    dequant/idct/clip per candidate (the rest of the per-candidate work
    was already paid) and is the bulk of hq's extra device cycles.
    Returns (mode (...,), levels_zz (..., 16), recon (..., 4, 4),
    score (...,)).
    """
    if lam is not None:
        lam_b = jnp.asarray(lam, jnp.float32)
        lam_b = lam_b.reshape(lam_b.shape + (1,) * (blk.ndim - 2 - lam_b.ndim))
        cands = []
        for p, lg in zip(preds, legal):
            w = _fwd4x4(blk - p)
            lv = quant.h264_quantize_4x4(w, qp, intra=True)
            rec = jnp.clip(p + _inv4x4(quant.h264_dequantize_4x4(lv, qp)),
                           0, 255)
            c = (_ssd(rec, blk, (-2, -1)).astype(jnp.float32)
                 + lam_b * _level_bits_est(lv, (-2, -1)))
            if lg is not True:
                c = jnp.where(lg, c, jnp.inf)
            cands.append((lv, rec, c))
        c = jnp.stack([cd[2] for cd in cands])         # (K, ...)
        k = jnp.argmin(c, axis=0)
        score = jnp.min(c, axis=0)
        lv, rec = cands[0][0], cands[0][1]
        for i in range(1, len(cands)):
            m = (k == i)[..., None, None]
            lv = jnp.where(m, cands[i][0], lv)
            rec = jnp.where(m, cands[i][1], rec)
        mode = jnp.asarray(modes, jnp.int32)[k]
        lvz = lv.reshape(lv.shape[:-2] + (16,))[..., jnp.asarray(ZIGZAG4)]
        return mode, lvz, rec, score
    cands = []
    for p, lg in zip(preds, legal):
        w = _fwd4x4(blk - p)
        lv = quant.h264_quantize_4x4(w, qp, intra=True)  # FULL 4x4, no DC
        b = _level_bits_est(lv, (-2, -1))
        if lg is not True:
            b = jnp.where(lg, b, 1 << 30)
        cands.append((lv, p, b))
    b = jnp.stack([c[2] for c in cands])               # (K, ...)
    k = jnp.argmin(b, axis=0)
    bits = jnp.min(b, axis=0)
    lv, pred = cands[0][0], cands[0][1]
    for i in range(1, len(cands)):
        m = (k == i)[..., None, None]
        lv = jnp.where(m, cands[i][0], lv)
        pred = jnp.where(m, cands[i][1], pred)
    mode = jnp.asarray(modes, jnp.int32)[k]
    wr = quant.h264_dequantize_4x4(lv, qp)
    rec = jnp.clip(pred + _inv4x4(wr), 0, 255)
    lvz = lv.reshape(lv.shape[:-2] + (16,))[..., jnp.asarray(ZIGZAG4)]
    return mode, lvz, rec, bits


def _hu_pred(left):
    """Horizontal-Up (mode 8) from left samples L0..L3: (..., 4) -> 4x4."""
    l0, l1, l2, l3 = (left[..., i] for i in range(4))
    z = [(l0 + l1 + 1) >> 1,                 # zHU 0
         (l0 + 2 * l1 + l2 + 2) >> 2,        # 1
         (l1 + l2 + 1) >> 1,                 # 2
         (l1 + 2 * l2 + l3 + 2) >> 2,        # 3
         (l2 + l3 + 1) >> 1,                 # 4
         (l2 + 3 * l3 + 2) >> 2,             # 5
         l3, l3]                             # >= 6
    rows = [jnp.stack([z[min(x + 2 * y, 7)] for x in range(4)], axis=-1)
            for y in range(4)]
    return jnp.stack(rows, axis=-2)          # (..., 4, 4)


def _vert_preds(p8):
    """Vertical-family predictions from top samples p[0..7,-1]: (..., 8).

    Returns (V, DDL, VL), each (..., 4, 4)."""
    p = [p8[..., i] for i in range(8)]
    v = jnp.stack([jnp.stack([p[x] for x in range(4)], axis=-1)] * 4,
                  axis=-2)
    def ddl(y, x):
        i = x + y
        if i == 6:                                   # x == 3 and y == 3
            return (p[6] + 3 * p[7] + 2) >> 2
        return (p[i] + 2 * p[i + 1] + p[i + 2] + 2) >> 2
    ddl_m = jnp.stack([jnp.stack([ddl(y, x) for x in range(4)], axis=-1)
                       for y in range(4)], axis=-2)
    def vl(y, x):
        i = x + (y >> 1)
        if y % 2 == 0:
            return (p[i] + p[i + 1] + 1) >> 1
        return (p[i] + 2 * p[i + 1] + p[i + 2] + 2) >> 2
    vl_m = jnp.stack([jnp.stack([vl(y, x) for x in range(4)], axis=-1)
                      for y in range(4)], axis=-2)
    return v, ddl_m, vl_m


def _diag_preds(t8, l4, tl):
    """The three both-neighbor diagonal modes from top t8 (..., 8), left
    l4 (..., 4) and top-left tl (...,): (DDR, VR, HD), each (..., 4, 4)
    — spec 8.3.1.2.4-6."""
    t = [t8[..., i] for i in range(8)]
    l_ = [l4[..., i] for i in range(4)]

    def tt(i):                       # t with index -1 = top-left
        return tl if i < 0 else t[i]

    def ll(i):
        return tl if i < 0 else l_[i]

    def ddr(y, x):
        d = x - y
        if d > 0:
            return (tt(d - 2) + 2 * tt(d - 1) + tt(d) + 2) >> 2
        if d < 0:
            return (ll(-d - 2) + 2 * ll(-d - 1) + ll(-d) + 2) >> 2
        return (t[0] + 2 * tl + l_[0] + 2) >> 2

    def vr(y, x):
        z = 2 * x - y
        if z >= 0:
            i = x - (y >> 1)
            if z % 2 == 0:
                return (tt(i - 1) + tt(i) + 1) >> 1
            return (tt(i - 2) + 2 * tt(i - 1) + tt(i) + 2) >> 2
        if z == -1:
            return (l_[0] + 2 * tl + t[0] + 2) >> 2
        return (ll(y - 2 * x - 1) + 2 * ll(y - 2 * x - 2)
                + ll(y - 2 * x - 3) + 2) >> 2

    def hd(y, x):
        z = 2 * y - x
        if z >= 0:
            i = y - (x >> 1)
            if z % 2 == 0:
                return (ll(i - 1) + ll(i) + 1) >> 1
            return (ll(i - 2) + 2 * ll(i - 1) + ll(i) + 2) >> 2
        if z == -1:
            return (l_[0] + 2 * tl + t[0] + 2) >> 2
        return (tt(x - 2 * y - 1) + 2 * tt(x - 2 * y - 2)
                + tt(x - 2 * y - 3) + 2) >> 2

    def grid(f):
        return jnp.stack([jnp.stack([f(y, x) for x in range(4)], axis=-1)
                          for y in range(4)], axis=-2)

    return grid(ddr), grid(vr), grid(hd)


def _acc_score(total, score, lam):
    """Accumulate a block score into the MB total, clamping the illegal
    sentinel (int 1<<30 / float inf) so a sum cannot overflow/poison."""
    if lam is None:
        return total + jnp.minimum(score, 1 << 24)
    return total + jnp.minimum(score, jnp.float32(1e18))


def _i4_row0(ymb, left_col, has_left, qp, rec, raster_mode, raster_lvz,
             bits_total, lam=None):
    """Block row by=0 (top of the slice: no samples above): four
    bx-sequential blocks with the LEFT-family modes {H, HU, DC(left)}.
    Shared by the fast and full I4 paths."""
    nr = ymb.shape[0]
    for bx in range(4):
        blk = ymb[:, 0:4, bx * 4:bx * 4 + 4]
        if bx == 0:
            left4 = left_col[:, 0:4]
            avail = jnp.broadcast_to(has_left, (nr,))
        else:
            left4 = rec[:, 0:4, bx * 4 - 1]
            avail = jnp.ones((nr,), bool)
        pred_h = jnp.broadcast_to(left4[:, :, None], (nr, 4, 4))
        pred_hu = _hu_pred(left4)
        dc = jnp.where(avail, (left4.sum(axis=1) + 2) >> 2, 128)
        pred_dc = jnp.broadcast_to(dc[:, None, None], (nr, 4, 4))
        mode, lvz, rb, bits = _i4_code_block(
            blk, [pred_h, pred_hu, pred_dc], [1, 8, 2],
            [avail, avail, True], qp, lam=lam)
        rec = rec.at[:, 0:4, bx * 4:bx * 4 + 4].set(rb)
        raster_mode[(0, bx)] = mode
        raster_lvz[(0, bx)] = lvz
        bits_total = _acc_score(bits_total, bits, lam)
    return rec, bits_total


def _i4_stack(raster_mode, raster_lvz):
    """Raster dicts -> (levels (R, 16 blkIdx, 16), modes (R, 16 blkIdx))
    in luma4x4BlkIdx order."""
    modes = jnp.stack([raster_mode[(by, bx)]
                       for (bx, by) in LUMA_BLOCK_ORDER], axis=1)
    levels = jnp.stack([raster_lvz[(by, bx)]
                        for (bx, by) in LUMA_BLOCK_ORDER], axis=1)
    return levels, modes


def _i4_score0(nr, lam):
    return jnp.zeros((nr,), jnp.int32 if lam is None else jnp.float32)


def _luma_step_i4_full(ymb, left_col, has_left, qp, lam=None):
    """I4x4 with the FULL nine-mode set on block rows 1-3.

    Same contract as :func:`_luma_step_i4`.  The left-family and
    both-neighbor modes (H, HU, DDR, VR, HD, two-sided DC) make each
    block depend on its in-row left neighbor's reconstruction, so rows
    1-3 run bx-SEQUENTIALLY here (16 sub-steps per MB column vs 7) —
    measurably better compression for measurably more sequential depth;
    selected via i16_modes="full" (ENCODER_INTRA_MODES=full)."""
    nr = ymb.shape[0]
    rec = jnp.zeros_like(ymb)
    raster_mode = {}
    raster_lvz = {}
    bits_total = _i4_score0(nr, lam)
    rec, bits_total = _i4_row0(ymb, left_col, has_left, qp, rec,
                               raster_mode, raster_lvz, bits_total,
                               lam=lam)

    # block rows 1-3: all nine modes, sequential along bx
    for by in range(1, 4):
        y0 = by * 4
        for bx in range(4):
            blk = ymb[:, y0:y0 + 4, bx * 4:bx * 4 + 4]
            trow = rec[:, y0 - 1, bx * 4:bx * 4 + 4]            # (R, 4)
            if bx < 3 and _TR_AVAIL[by, bx]:
                tr = rec[:, y0 - 1, bx * 4 + 4:bx * 4 + 8]
            else:
                tr = jnp.broadcast_to(trow[:, 3:4], trow.shape)
            t8 = jnp.concatenate([trow, tr], axis=1)            # (R, 8)
            if bx == 0:
                l4 = left_col[:, y0:y0 + 4]
                tl = left_col[:, y0 - 1]
                avail = jnp.broadcast_to(has_left, (nr,))
            else:
                l4 = rec[:, y0:y0 + 4, bx * 4 - 1]
                tl = rec[:, y0 - 1, bx * 4 - 1]
                avail = jnp.ones((nr,), bool)
            v, ddl, vl = _vert_preds(t8)
            ddr, vr, hd = _diag_preds(t8, l4, tl)
            pred_h = jnp.broadcast_to(l4[:, :, None], (nr, 4, 4))
            pred_hu = _hu_pred(l4)
            # DC: both-available averages top+left; top-only otherwise
            # (the decoder applies the same availability rule, 8.3.1.2.3)
            dc_both = (t8[:, :4].sum(axis=1) + l4.sum(axis=1) + 4) >> 3
            dc_top = (t8[:, :4].sum(axis=1) + 2) >> 2
            dc = jnp.where(avail, dc_both, dc_top)
            pred_dc = jnp.broadcast_to(dc[:, None, None], (nr, 4, 4))
            mode, lvz, rb, bits = _i4_code_block(
                blk,
                [v, ddl, vl, pred_dc, pred_h, pred_hu, ddr, vr, hd],
                [0, 3, 7, 2, 1, 8, 4, 5, 6],
                [True, True, True, True, avail, avail, avail, avail,
                 avail], qp, lam=lam)
            rec = rec.at[:, y0:y0 + 4, bx * 4:bx * 4 + 4].set(rb)
            raster_mode[(by, bx)] = mode
            raster_lvz[(by, bx)] = lvz
            bits_total = _acc_score(bits_total, bits, lam)

    levels, modes = _i4_stack(raster_mode, raster_lvz)
    return levels, modes, rec, bits_total


def _luma_step_i4(ymb, left_col, has_left, qp, lam=None):
    """I4x4 candidate for one MB column across all rows.

    ymb: (R, 16, 16) int32; left_col: (R, 16).  Returns
    (levels (R, 16 blkIdx, 16 zigzag), modes (R, 16 blkIdx),
    recon (R, 16, 16), score (R,) — estimated bits, or SSD + lam * bits
    under tune=hq)."""
    nr = ymb.shape[0]
    rec = jnp.zeros_like(ymb)
    raster_mode = {}
    raster_lvz = {}
    bits_total = _i4_score0(nr, lam)
    rec, bits_total = _i4_row0(ymb, left_col, has_left, qp, rec,
                               raster_mode, raster_lvz, bits_total,
                               lam=lam)

    # --- block rows by=1..3: all bx parallel, vertical-family modes ----
    for by in range(1, 4):
        blks = ymb[:, by * 4:by * 4 + 4, :]
        blks = blks.reshape(nr, 4, 4, 4).transpose(0, 2, 1, 3)  # (R,bx,y,x)
        trow = rec[:, by * 4 - 1, :].reshape(nr, 4, 4)          # (R,bx,4)
        # p[4..7,-1]: above-right block's bottom row when its z-order
        # predecessor status allows, else the spec's p[3,-1] substitution
        tr = jnp.concatenate([trow[:, 1:], trow[:, 3:, :]], axis=1)
        sub = jnp.broadcast_to(trow[:, :, 3:4], trow.shape)
        avail_tr = jnp.asarray(_TR_AVAIL[by])[None, :, None]    # (1,bx,1)
        tr = jnp.where(avail_tr, tr, sub)
        p8 = jnp.concatenate([trow, tr], axis=2)                # (R,bx,8)
        v, ddl, vl = _vert_preds(p8)
        mode, lvz, rb, bits = _i4_code_block(
            blks, [v, ddl, vl], [0, 3, 7], [True, True, True], qp,
            lam=lam)
        rb = rb.transpose(0, 2, 1, 3).reshape(nr, 4, 16)
        rec = rec.at[:, by * 4:by * 4 + 4, :].set(rb)
        for bx in range(4):
            raster_mode[(by, bx)] = mode[:, bx]
            raster_lvz[(by, bx)] = lvz[:, bx]
        bits_total = bits_total + bits.sum(axis=1)

    levels, modes = _i4_stack(raster_mode, raster_lvz)
    return levels, modes, rec, bits_total


@functools.partial(jax.jit,
                   static_argnames=("pad_h", "pad_w", "qp", "i16_modes",
                                    "tune"))
def encode_intra_frame(rgb, pad_h: int, pad_w: int, qp: int,
                       i16_modes: str = "auto", tune: str = "off",
                       next_y=None):
    """Full device stage: RGB frame -> quantized level tensors + recon.

    Returns a dict of int32/uint8 arrays (see keys below); shapes use
    R = pad_h//16 MB rows and C = pad_w//16 MB columns.
    """
    h, w = rgb.shape[0], rgb.shape[1]
    rgb_p = jnp.pad(jnp.asarray(rgb), ((0, pad_h - h), (0, pad_w - w), (0, 0)),
                    mode="edge")
    yf, cbf, crf = color.rgb_to_yuv420(rgb_p, matrix="video")
    y = jnp.clip(jnp.round(yf), 0, 255).astype(jnp.int32)
    cb = jnp.clip(jnp.round(cbf), 0, 255).astype(jnp.int32)
    cr = jnp.clip(jnp.round(crf), 0, 255).astype(jnp.int32)
    return encode_intra_frame_yuv.__wrapped__(y, cb, cr, qp, i16_modes,
                                              tune, next_y)


@functools.partial(jax.jit, static_argnames=("qp", "i16_modes", "tune"))
def encode_intra_frame_yuv(y, cb, cr, qp: int, i16_modes: str = "auto",
                           tune: str = "off", next_y=None):
    """Same device stage from pre-converted YUV 4:2:0 planes (already padded
    to macroblock multiples).  The host-side capture path converts RGB with
    cv2 (BT.601 studio range, matching ops/color "video") and ships 1.5
    bytes/pixel instead of 3 — the host->device link is the hot-path
    bottleneck (SURVEY.md §3.2 PCIe budget).

    ``i16_modes``: "auto" = per-MB choice among I16 DC/H and the I4x4
    path (fast mode sets); "full" = same but I4x4 block rows 1-3 search
    all NINE prediction modes (bx-sequential; ~2x the intra sequential
    depth for measurably fewer bits); "i16" = I16 DC/H only; "dc" = I16
    DC only (the native host entropy coder has no mode plumbing).

    I16x16 Vertical and Plane are NOT mode-set gaps: under slice-per-MB-
    row the macroblock above is always in a different slice, and samples
    outside the slice are unavailable for intra prediction (spec 6.4.9 /
    8.3.3) — DC and Horizontal are the only LEGAL I16 modes in this
    geometry, for this encoder and for NVENC alike.

    ``tune`` (ENCODER_TUNE): "off" keeps every decision and output
    byte-identical to the pre-tune encoder.  "hq" adds (a) per-MB
    adaptive quantization — a qp plane from luma activity (ops/aq),
    plus a 1-frame lookahead bias when ``next_y`` is staged — and (b)
    Lagrangian D + lambda(qp) * R mode decisions for every intra
    choice.  "hq_noaq" keeps the lambda decisions but pins the qp plane
    flat (the deblock-enabled variant: the loop filter's thresholds are
    compiled per-slice-qp, so per-MB qp is v1-limited to deblock-off)."""
    y = jnp.asarray(y).astype(jnp.int32)
    cb = jnp.asarray(cb).astype(jnp.int32)
    cr = jnp.asarray(cr).astype(jnp.int32)
    if tune not in ("off", "hq", "hq_noaq"):
        raise ValueError(f"unknown tune {tune!r}")
    pad_h, pad_w = y.shape
    nr, nc = pad_h // 16, pad_w // 16
    allow_i4 = i16_modes in ("auto", "full")
    i4_step = _luma_step_i4_full if i16_modes == "full" else _luma_step_i4
    # I4's extra signaling vs I16: 16 mode elements (~1-4 b) + cbp ue
    # against the I16 combined mb_type — ~44 bits on the bit-estimate
    # scale of _level_bits_est.
    i4_sig_bits = 44

    qp_map = None
    if tune == "hq":
        from . import aq
        qp_map = aq.qp_plane(y, qp, next_y)             # (R, C) absolute
        qpmbs = jnp.moveaxis(qp_map, 0, 1)              # (C, R) scan axis
        qcmbs = jnp.moveaxis(quant.chroma_qp_v(qp_map), 0, 1)
    else:
        qp_c = quant.chroma_qp(qp)

    # (C, R, ...) layouts: scan axis leading.
    ymbs = jnp.moveaxis(
        y.reshape(nr, 16, nc, 16).transpose(0, 2, 1, 3), 1, 0)
    cbmbs = jnp.moveaxis(
        cb.reshape(nr, 8, nc, 8).transpose(0, 2, 1, 3), 1, 0)
    crmbs = jnp.moveaxis(
        cr.reshape(nr, 8, nc, 8).transpose(0, 2, 1, 3), 1, 0)

    def step(carry, xs):
        yl, cbl, crl = carry
        if tune == "hq":
            ymb, cbmb, crmb, idx, qp_s, qc_s = xs
            lam = None
            from . import aq
            lam = aq.lam_mode(qp_s)                     # (R,) float32
        else:
            ymb, cbmb, crmb, idx = xs
            qp_s, qc_s = qp, qp_c
            lam = None
            if tune == "hq_noaq":
                from . import aq
                lam = float(aq.lam_mode(qp))
        has_left = idx > 0
        y_ac, y_dc, y_rec, y_mode, bits16 = _luma_step(
            ymb, yl, has_left, qp_s, allow_h=i16_modes != "dc", lam=lam)
        if allow_i4:
            lv4, modes4, rec4, bits4 = i4_step(ymb, yl, has_left, qp_s,
                                               lam=lam)
            if lam is None:
                use4 = bits4 + i4_sig_bits < bits16         # (R,)
            else:
                use4 = bits4 + lam * i4_sig_bits < bits16
            y_rec = jnp.where(use4[:, None, None], rec4, y_rec)
        else:
            lv4 = jnp.zeros((ymb.shape[0], 16, 16), jnp.int32)
            modes4 = jnp.full((ymb.shape[0], 16), 2, jnp.int32)
            use4 = jnp.zeros((ymb.shape[0],), bool)
        cb_ac, cb_dc, cb_rec = _chroma_step(cbmb, cbl, has_left, qc_s)
        cr_ac, cr_dc, cr_rec = _chroma_step(crmb, crl, has_left, qc_s)
        carry = (y_rec[:, :, 15], cb_rec[:, :, 7], cr_rec[:, :, 7])
        out = (y_ac, y_dc, cb_ac, cb_dc, cr_ac, cr_dc,
               y_rec.astype(jnp.uint8), cb_rec.astype(jnp.uint8),
               cr_rec.astype(jnp.uint8), y_mode, lv4, modes4, use4)
        return carry, out

    init = (jnp.zeros((nr, 16), jnp.int32), jnp.zeros((nr, 8), jnp.int32),
            jnp.zeros((nr, 8), jnp.int32))
    xs = (ymbs, cbmbs, crmbs, jnp.arange(nc, dtype=jnp.int32))
    if tune == "hq":
        xs = xs + (qpmbs, qcmbs)
    _, outs = jax.lax.scan(step, init, xs)
    (y_ac, y_dc, cb_ac, cb_dc, cr_ac, cr_dc, y_rec, cb_rec, cr_rec,
     y_mode, y_lv4, y_modes4, y_use4) = outs
    # scan stacked along axis 0 = columns; put rows first: (R, C, ...)
    to_rc = lambda a: jnp.moveaxis(a, 0, 1)

    # --- scan-order reordering (device-side gathers) ---
    zz = jnp.asarray(ZIGZAG4)
    blk = jnp.asarray(LUMA_BLOCK_ORDER)

    y_ac = to_rc(y_ac)                                 # (R, C, by, bx, 4, 4)
    y_acf = y_ac.reshape(nr, nc, 4, 4, 16)[..., zz[1:]]  # zigzag, AC only
    # gather blocks into luma4x4BlkIdx order: index [by, bx] per blkIdx
    y_acf = y_acf[:, :, blk[:, 1], blk[:, 0], :]       # (R, C, 16, 15)

    y_dcf = to_rc(y_dc).reshape(nr, nc, 16)[..., zz]   # (R, C, 16)

    def chroma_fmt(ac, dc):
        ac = to_rc(ac).reshape(nr, nc, 4, 16)[..., zz[1:]]  # blocks raster
        dc = to_rc(dc).reshape(nr, nc, 4)
        return ac, dc

    cb_acf, cb_dcf = chroma_fmt(cb_ac, cb_dc)
    cr_acf, cr_dcf = chroma_fmt(cr_ac, cr_dc)

    # recon planes reassembled for tests / PSNR
    y_full = to_rc(y_rec).transpose(0, 2, 1, 3).reshape(pad_h, pad_w)
    cb_full = to_rc(cb_rec).transpose(0, 2, 1, 3).reshape(pad_h // 2, pad_w // 2)
    cr_full = to_rc(cr_rec).transpose(0, 2, 1, 3).reshape(pad_h // 2, pad_w // 2)

    out = {
        "luma_dc": y_dcf,        # (R, C, 16) zigzag
        "luma_ac": y_acf,        # (R, C, 16 blkIdx, 15) zigzag
        "cb_dc": cb_dcf,         # (R, C, 4) raster
        "cb_ac": cb_acf,         # (R, C, 4 raster, 15)
        "cr_dc": cr_dcf,
        "cr_ac": cr_acf,
        "pred_mode": to_rc(y_mode),   # (R, C) Intra16x16PredMode (1=H, 2=DC)
        "mb_i4": to_rc(y_use4),       # (R, C) MB coded I_NxN
        "i4_modes": to_rc(y_modes4),  # (R, C, 16 blkIdx) Intra4x4PredMode
        "luma_i4": to_rc(y_lv4),      # (R, C, 16 blkIdx, 16) zigzag levels
        "recon_y": y_full, "recon_cb": cb_full, "recon_cr": cr_full,
    }
    if qp_map is not None:
        out["qp_map"] = qp_map        # (R, C) absolute per-MB qp (tune=hq)
    return out
