"""Perceptual-efficiency tuning kernels (ENCODER_TUNE=hq, ROADMAP item 4).

Three device-side pieces that trade device cycles for bits — the NVENC
tuning-ladder analog (PAPERS.md: "Evolution of NVENC Efficiency"):

1. **Adaptive per-MB quantization** (:func:`aq_offsets`): a per-MB QP
   delta plane from luma activity (variance), computed as one reduction
   over the already-tiled 16x16 blocks.  Low-activity (flat) macroblocks
   quantize finer — they are cheap in bits and visually/numerically
   dominant; high-activity blocks absorb coarser quantization.  The map
   is a PURE PER-MB function (log-activity against a fixed reference
   energy, no frame-level normalization), which is what makes it safe in
   every execution shape: the spatially-sharded mesh, the donated-ring
   chunk scan, and the per-frame path all compute identical planes.
   The frame's mean coded QP therefore moves with content; the
   RateController normalizes its +6-qp-halves-bits model by the *mean
   coded* QP, not the nominal ladder value (models/h264).

2. **Lambda tables** (:func:`lam_mode` / :func:`lam_mv`): the standard
   H.264 Lagrangian lambda(QP) = 0.85 * 2^((QP-12)/3) for SSD-domain
   mode decisions and its square root for SAD-domain motion decisions.
   Mode/MV choices then minimize D + lambda * R instead of the fixed
   bits-only / fixed-SAD-margin heuristics.

3. **1-frame lookahead bias** (:func:`lookahead_bias`): per-MB SAD
   between the current and NEXT frame (the chunk ring's already-staged
   frames — zero extra transfers).  Static content earns a negative
   delta (its quality propagates through the P chain), fast-changing
   content a positive one (those bits are washed away next frame).

Everything here is elementwise/reduction VPU work that XLA fuses into
the surrounding encode kernels; tune=off paths never call into this
module, which is what keeps them byte-identical to the pre-tune output.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..utils.env import env_float as _envf

__all__ = ["aq_offsets", "lookahead_bias", "lam_mode", "lam_mv",
           "qp_plane", "qp_chain", "qp_chain_np", "mse_planes",
           "AQ_STRENGTH", "AQ_MAX_DELTA", "AQ_MAX_UP", "LOOKAHEAD_BIAS"]

# Operator knobs (read once at import, like DNGD_RING_DONATE): strength
# in ~x264 aq-strength units, the delta clamps, and the lookahead reward.
# The up/down clamps are ASYMMETRIC by default: lifting flat blocks
# (negative delta) buys PSNR cheaply — they cost few bits — while
# coarsening busy blocks trades a lot of measured distortion for modest
# savings, so the up side caps at +1 (the perceptual-masking headroom
# is real but the BD-rate harness scores PSNR, and a +1 cap keeps hq
# strictly non-losing there while still shaving busy-block bits).
# env_float degrades a typo'd knob to its default with a warning — a
# malformed value must not fail every hq encode at first import.
AQ_STRENGTH = _envf("DNGD_AQ_STRENGTH", 1.0)
AQ_MAX_DELTA = int(_envf("DNGD_AQ_MAX_DELTA", 4))
AQ_MAX_UP = int(_envf("DNGD_AQ_MAX_UP", 1))
LOOKAHEAD_BIAS = int(_envf("DNGD_LOOKAHEAD_BIAS", 2))

# Reference log2 activity: a 16x16 block whose summed squared deviation
# (256 * per-pixel variance) is ~2^_AQ_REF_LOG sits at delta 0.  12.0
# corresponds to per-pixel variance 16 — typical desktop-content
# mid-energy (empirically centers the map on the bench's three content
# classes).
_AQ_REF_LOG = 12.0


def _mb_reduce(plane, op):
    """(H, W) -> (R, C) per-16x16-MB reduction."""
    h, w = plane.shape
    t = plane.reshape(h // 16, 16, w // 16, 16)
    return op(t, (1, 3))


def mb_activity(y):
    """Per-MB luma activity: sum of squared deviation from the MB mean
    (256 * variance), int32-exact.  One reduction over the tiled plane."""
    yi = jnp.asarray(y, jnp.int32)
    s = _mb_reduce(yi, jnp.sum)                       # (R, C)
    s2 = _mb_reduce(yi * yi, jnp.sum)
    # 256 * var = sum(x^2) - sum(x)^2 / 256; keep integer via * 256
    return jnp.maximum(256 * s2 - s * s, 0)           # (R, C) ~2^24 max


def aq_offsets(y, strength: float = None, max_delta: int = None):
    """Per-MB QP delta plane from luma activity.

    delta = round(strength * (log2(act + 1) - REF) / 2) clipped to
    [-max_delta, +AQ_MAX_UP], where act = mb_activity/256 is the MB's
    summed squared deviation (256x the per-pixel variance).  The /2
    maps one doubling of activity to ~strength/2 qp steps — the x264
    aq-mode-1 slope; the asymmetric clip is PSNR-guarding (see the knob
    comment above).  Pure per-MB math: shard/chunk/per-frame agree."""
    s = AQ_STRENGTH if strength is None else float(strength)
    md = AQ_MAX_DELTA if max_delta is None else int(max_delta)
    act = mb_activity(y).astype(jnp.float32) / 256.0
    d = s * 0.5 * (jnp.log2(act + 1.0) - _AQ_REF_LOG)
    return jnp.clip(jnp.round(d), -md, min(AQ_MAX_UP, md)).astype(jnp.int32)


def lookahead_bias(y, next_y, bias: int = None):
    """Per-MB QP bias from the NEXT frame: -bias where the block barely
    changes (quality propagates through the P chain), +1 where it
    changes heavily (bits are washed away next frame), 0 between.
    Thresholds are per-pixel mean-abs-diff 1.0 / 6.0."""
    b = LOOKAHEAD_BIAS if bias is None else int(bias)
    d = jnp.abs(jnp.asarray(y, jnp.int32) - jnp.asarray(next_y, jnp.int32))
    sad = _mb_reduce(d, jnp.sum)                      # (R, C), /256 = mean
    return jnp.where(sad <= 256, -b,
                     jnp.where(sad >= 6 * 256, 1, 0)).astype(jnp.int32)


def qp_plane(y, qp: int, next_y=None, strength: float = None,
             max_delta: int = None):
    """The hq paths' per-MB ABSOLUTE qp map: ladder qp + activity delta
    (+ lookahead bias when the next frame is staged), clipped to the
    coded range.  qp stays >= 1 so the se(v) slot widths stay tiny."""
    d = aq_offsets(y, strength, max_delta)
    if next_y is not None:
        d = d + lookahead_bias(y, next_y)
    return jnp.clip(qp + d, 1, 51).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Lagrangian lambdas (H.264 HM/JM convention)
# ---------------------------------------------------------------------------

def lam_mode(qp):
    """SSD-domain mode-decision lambda: 0.85 * 2^((qp-12)/3).  Accepts a
    static int (returns a Python float) or a per-MB array."""
    if isinstance(qp, (int, np.integer)):
        return 0.85 * 2.0 ** ((int(qp) - 12) / 3.0)
    q = jnp.asarray(qp, jnp.float32)
    return 0.85 * jnp.exp2((q - 12.0) / 3.0)


def lam_mv(qp):
    """SAD-domain motion lambda: sqrt(lam_mode)."""
    if isinstance(qp, (int, np.integer)):
        return float(np.sqrt(lam_mode(qp)))
    return jnp.sqrt(lam_mode(qp))


# ---------------------------------------------------------------------------
# mb_qp_delta chain (spec 7.4.5: QPY carries from the previous MB in
# decoding order; slice-per-row resets each row to the slice QP)
# ---------------------------------------------------------------------------

def qp_chain(qp_map, codes_delta, slice_qp: int):
    """Per-row effective-QP chain and the per-MB mb_qp_delta values.

    qp_map: (R, C) desired per-MB qp; codes_delta: (R, C) bool — the MBs
    whose syntax carries mb_qp_delta (I16 always; otherwise cbp != 0).
    Returns (eff_qp, delta): an MB that does not code the syntax keeps
    the previous MB's effective qp (delta is meaningless there and its
    slot is gated off by the caller).  eff_qp is what the deblocking
    filter would see; MBs without coefficients never dequantize, so
    quantizing everything at qp_map stays conformant.
    """
    qp_map = jnp.asarray(qp_map, jnp.int32)
    codes = jnp.asarray(codes_delta, bool)
    nr, nc = qp_map.shape
    idx = jnp.arange(nc, dtype=jnp.int32)[None, :]
    import jax
    j = jax.lax.cummax(jnp.where(codes, idx, -1), axis=1)  # last coded <= c
    eff = jnp.where(j >= 0,
                    jnp.take_along_axis(qp_map, jnp.clip(j, 0), axis=1),
                    slice_qp)
    prev = jnp.concatenate(
        [jnp.full((nr, 1), slice_qp, jnp.int32), eff[:, :-1]], axis=1)
    return eff, (qp_map - prev)


def qp_chain_np(qp_map: np.ndarray, codes_delta: np.ndarray,
                slice_qp: int):
    """Numpy twin of :func:`qp_chain` for the host entropy coders."""
    qp_map = np.asarray(qp_map, np.int32)
    codes = np.asarray(codes_delta, bool)
    nr, nc = qp_map.shape
    idx = np.arange(nc, dtype=np.int32)[None, :]
    j = np.maximum.accumulate(np.where(codes, idx, -1), axis=1)
    eff = np.where(j >= 0,
                   np.take_along_axis(qp_map, np.clip(j, 0, None), axis=1),
                   slice_qp).astype(np.int32)
    prev = np.concatenate(
        [np.full((nr, 1), slice_qp, np.int32), eff[:, :-1]], axis=1)
    return eff, (qp_map - prev).astype(np.int32)


# ---------------------------------------------------------------------------
# Device-side distortion reductions (the BD-rate bench's PSNR input)
# ---------------------------------------------------------------------------

def _mse_reduce(x, y):
    d = x.astype(jnp.int32) - y.astype(jnp.int32)
    return jnp.sum((d * d).astype(jnp.int64))


_mse_jit = None      # jitted lazily so importing aq never inits a backend


def mse_planes(a, b):
    """Mean squared error between two planes as ONE device reduction
    (float64-free: int64 SSE over uint8 planes is exact)."""
    global _mse_jit
    if _mse_jit is None:
        import jax
        _mse_jit = jax.jit(_mse_reduce)
    sse = float(np.asarray(_mse_jit(jnp.asarray(a), jnp.asarray(b))))
    n = int(np.prod(np.asarray(a).shape))
    return sse / max(n, 1)


def psnr_planes(a, b) -> float:
    m = mse_planes(a, b)
    if m <= 0:
        return 99.0
    return float(10.0 * np.log10(255.0 * 255.0 / m))
