"""Quantization: JPEG Annex-K tables with libjpeg quality scaling, and the
H.264 integer quant/dequant (MF/V) machinery.

On TPU quantization is elementwise multiply + shift over the blocked
coefficient tensor — pure VPU work that XLA fuses with the preceding
transform.  The H.264 path reproduces the JM/x264 fixed-point formulation:

    level  = sign(w) * ((|w| * MF[qp%6] + f) >> qbits),   qbits = 15 + qp//6
    w'     = level * V[qp%6] << (qp//6)                    (AC dequant)

so reconstruction matches conformant decoders exactly.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

# ---------------------------------------------------------------------------
# JPEG (ITU T.81 Annex K) base tables + libjpeg quality scaling
# ---------------------------------------------------------------------------

JPEG_LUMA_Q = np.array(
    [
        [16, 11, 10, 16, 24, 40, 51, 61],
        [12, 12, 14, 19, 26, 58, 60, 55],
        [14, 13, 16, 24, 40, 57, 69, 56],
        [14, 17, 22, 29, 51, 87, 80, 62],
        [18, 22, 37, 56, 68, 109, 103, 77],
        [24, 35, 55, 64, 81, 104, 113, 92],
        [49, 64, 78, 87, 103, 121, 120, 101],
        [72, 92, 95, 98, 112, 100, 103, 99],
    ],
    dtype=np.int32,
)

JPEG_CHROMA_Q = np.array(
    [
        [17, 18, 24, 47, 99, 99, 99, 99],
        [18, 21, 26, 66, 99, 99, 99, 99],
        [24, 26, 56, 99, 99, 99, 99, 99],
        [47, 66, 99, 99, 99, 99, 99, 99],
        [99, 99, 99, 99, 99, 99, 99, 99],
        [99, 99, 99, 99, 99, 99, 99, 99],
        [99, 99, 99, 99, 99, 99, 99, 99],
        [99, 99, 99, 99, 99, 99, 99, 99],
    ],
    dtype=np.int32,
)


def jpeg_quality_tables(quality: int):
    """libjpeg-style quality (1..100) scaling of the Annex-K tables."""
    quality = int(np.clip(quality, 1, 100))
    scale = 5000 // quality if quality < 50 else 200 - quality * 2
    luma = np.clip((JPEG_LUMA_Q * scale + 50) // 100, 1, 255).astype(np.int32)
    chroma = np.clip((JPEG_CHROMA_Q * scale + 50) // 100, 1, 255).astype(np.int32)
    return luma, chroma


def jpeg_quantize(coefs, table):
    """Round-to-nearest divide of DCT coefficients by the quant table."""
    t = jnp.asarray(table, jnp.float32)
    return jnp.round(jnp.asarray(coefs, jnp.float32) / t).astype(jnp.int32)


def jpeg_dequantize(levels, table):
    return jnp.asarray(levels, jnp.int32) * jnp.asarray(table, jnp.int32)


# ---------------------------------------------------------------------------
# H.264 quant (JM/x264 fixed-point; spec §8.5)
# ---------------------------------------------------------------------------

# MF (multiplication factor) per qp%6, by coefficient position class:
#   a: (0,0),(0,2),(2,0),(2,2)   b: (1,1),(1,3),(3,1),(3,3)   c: others
_MF_A = np.array([13107, 11916, 10082, 9362, 8192, 7282], dtype=np.int32)
_MF_B = np.array([5243, 4660, 4194, 3647, 3355, 2893], dtype=np.int32)
_MF_C = np.array([8066, 7490, 6554, 5825, 5243, 4559], dtype=np.int32)

# V (dequant scale) per qp%6, same position classes.
_V_A = np.array([10, 11, 13, 14, 16, 18], dtype=np.int32)
_V_B = np.array([16, 18, 20, 23, 25, 29], dtype=np.int32)
_V_C = np.array([13, 14, 16, 18, 20, 23], dtype=np.int32)


def _position_table(vec_a, vec_b, vec_c, dtype):
    """Build (6, 4, 4) tables from the three position-class vectors."""
    out = np.empty((6, 4, 4), dtype=dtype)
    for r in range(6):
        for i in range(4):
            for j in range(4):
                if (i % 2 == 0) and (j % 2 == 0):
                    out[r, i, j] = vec_a[r]
                elif (i % 2 == 1) and (j % 2 == 1):
                    out[r, i, j] = vec_b[r]
                else:
                    out[r, i, j] = vec_c[r]
    return out


MF_TABLE = _position_table(_MF_A, _MF_B, _MF_C, np.int32)   # (6,4,4)
V_TABLE = _position_table(_V_A, _V_B, _V_C, np.int32)       # (6,4,4)

# Chroma QP mapping for QPy 30..51 (below 30, QPc == QPy).  Spec Table 8-15.
_QPC_HIGH = np.array(
    [29, 30, 31, 32, 32, 33, 34, 34, 35, 35, 36, 36, 37, 37, 37, 38, 38, 38, 39, 39, 39, 39],
    dtype=np.int32,
)

# Full 0..51 chroma-QP table (offset 0) for the vector (per-MB qp) path.
QPC_TABLE = np.array(
    [q if q < 30 else int(_QPC_HIGH[q - 30]) for q in range(52)],
    dtype=np.int32)


def chroma_qp(qp_y: int, chroma_qp_index_offset: int = 0) -> int:
    q = int(np.clip(qp_y + chroma_qp_index_offset, 0, 51))
    return int(q) if q < 30 else int(_QPC_HIGH[q - 30])


def chroma_qp_v(qp_y):
    """Vector chroma QP: per-MB int32 array in, Table 8-15 mapped out."""
    q = jnp.clip(jnp.asarray(qp_y, jnp.int32), 0, 51)
    return jnp.asarray(QPC_TABLE)[q]


def _is_static_qp(qp) -> bool:
    """True for a Python/numpy scalar qp (the compile-time-constant path
    every pre-tune caller uses; kept byte-for-byte identical).  Traced
    arrays take the vector (per-MB) path below."""
    return isinstance(qp, (int, np.integer))


def _vq(qp, coefs_ndim: int, block_dims: int = 2):
    """Broadcast a per-MB qp array against coefficient leading dims:
    qp (...,) -> (..., 1, 1) aligned under ``block_dims`` trailing block
    axes.  The qp array must be broadcastable to coefs.shape[:-block_dims]."""
    q = jnp.asarray(qp, jnp.int32)
    extra = coefs_ndim - q.ndim - block_dims
    q = q.reshape(q.shape + (1,) * (block_dims + max(extra, 0)))
    return q


def h264_quantize_4x4(coefs, qp, intra: bool = True):
    """Quantize core-transform coefficients, trailing dims (4, 4).

    ``qp`` is either a static int (one compiled table constant — the
    pre-tune path, unchanged) or a per-MB int32 array broadcastable to
    the leading dims (the ENCODER_TUNE=hq adaptive-quantization path)."""
    w = jnp.asarray(coefs, jnp.int32)
    if _is_static_qp(qp):
        qbits = 15 + qp // 6
        mf = jnp.asarray(MF_TABLE[qp % 6])
        f = (1 << qbits) // 3 if intra else (1 << qbits) // 6
        level = (jnp.abs(w) * mf + f) >> qbits
        return (jnp.sign(w) * level).astype(jnp.int32)
    q = _vq(qp, w.ndim)
    qbits = 15 + q // 6
    mf = jnp.asarray(MF_TABLE)[(q % 6)[..., 0, 0]]   # (..., 4, 4) pos table
    f = jnp.left_shift(1, qbits) // (3 if intra else 6)
    level = (jnp.abs(w) * mf + f) >> qbits
    return (jnp.sign(w) * level).astype(jnp.int32)


def h264_dequantize_4x4(levels, qp):
    """Dequantize 4x4 AC levels per spec §8.5.12.1 (no rounding)."""
    lv = jnp.asarray(levels, jnp.int32)
    if _is_static_qp(qp):
        v = jnp.asarray(V_TABLE[qp % 6])
        return (lv * v) << (qp // 6)
    q = _vq(qp, lv.ndim)
    v = jnp.asarray(V_TABLE)[(q % 6)[..., 0, 0]]
    return (lv * v) << (q // 6)


def h264_quantize_luma_dc(dc_hadamard, qp):
    """Quantize the 4x4 Hadamard-transformed luma DC block (JM convention).

    Uses MF[qp%6][0,0] with an extra >>1 of headroom: qbits + 1.
    """
    w = jnp.asarray(dc_hadamard, jnp.int32)
    if _is_static_qp(qp):
        qbits = 15 + qp // 6
        mf00 = int(MF_TABLE[qp % 6][0, 0])
        f = (1 << qbits) // 3
        level = (jnp.abs(w) * mf00 + 2 * f) >> (qbits + 1)
        return (jnp.sign(w) * level).astype(jnp.int32)
    q = _vq(qp, w.ndim)
    qbits = 15 + q // 6
    mf00 = jnp.asarray(_MF_A)[q % 6]
    f = jnp.left_shift(1, qbits) // 3
    level = (jnp.abs(w) * mf00 + 2 * f) >> (qbits + 1)
    return (jnp.sign(w) * level).astype(jnp.int32)


def h264_dequantize_luma_dc(levels, qp):
    """Dequantize luma DC *after* the inverse Hadamard (spec §8.5.10).

    dcY = (f * V00 << (qp//6)) >> 2         if qp >= 12
        = (f * V00 + 2^(1 - qp//6)) >> (2 - qp//6)   otherwise
    """
    f = jnp.asarray(levels, jnp.int32)
    if _is_static_qp(qp):
        v00 = int(V_TABLE[qp % 6][0, 0])
        if qp >= 12:
            return (f * v00) << (qp // 6 - 2)
        shift = 2 - qp // 6
        return (f * v00 + (1 << (shift - 1))) >> shift
    q = _vq(qp, f.ndim)
    v00 = jnp.asarray(_V_A)[q % 6]
    hi = (f * v00) << jnp.maximum(q // 6 - 2, 0)
    shift = jnp.maximum(2 - q // 6, 1)          # qp < 12 -> shift in {1, 2}
    lo = (f * v00 + jnp.left_shift(1, shift - 1)) >> shift
    return jnp.where(q >= 12, hi, lo)


def h264_quantize_chroma_dc(dc_hadamard, qp_c, intra: bool = True):
    """Quantize the 2x2 Hadamard chroma DC (JM convention: qbits + 1)."""
    w = jnp.asarray(dc_hadamard, jnp.int32)
    if _is_static_qp(qp_c):
        qbits = 15 + qp_c // 6
        mf00 = int(MF_TABLE[qp_c % 6][0, 0])
        f = (1 << qbits) // 3 if intra else (1 << qbits) // 6
        level = (jnp.abs(w) * mf00 + 2 * f) >> (qbits + 1)
        return (jnp.sign(w) * level).astype(jnp.int32)
    q = _vq(qp_c, w.ndim)
    qbits = 15 + q // 6
    mf00 = jnp.asarray(_MF_A)[q % 6]
    f = jnp.left_shift(1, qbits) // (3 if intra else 6)
    level = (jnp.abs(w) * mf00 + 2 * f) >> (qbits + 1)
    return (jnp.sign(w) * level).astype(jnp.int32)


def h264_dequantize_chroma_dc(levels, qp_c):
    """Dequantize chroma DC after inverse 2x2 Hadamard (spec §8.5.11).

    dcC = ((f * V00) << (qp_c//6)) >> 1
    """
    f = jnp.asarray(levels, jnp.int32)
    if _is_static_qp(qp_c):
        v00 = int(V_TABLE[qp_c % 6][0, 0])
        return ((f * v00) << (qp_c // 6)) >> 1
    q = _vq(qp_c, f.ndim)
    v00 = jnp.asarray(_V_A)[q % 6]
    return ((f * v00) << (q // 6)) >> 1
