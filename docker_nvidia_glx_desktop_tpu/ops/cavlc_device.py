"""Device-side CAVLC entropy for the H.264 intra path.

Round 1 kept CAVLC on the host, which meant pulling the full quantized
level tensors (~8 MB/frame of int32) across the host<->device link every
frame — the entire 1 s/frame p50 (VERDICT weak #1).  This module moves the
whole entropy stage onto the TPU:

1. Every 4x4 residual block (27 per MB: luma DC, 16 luma AC, 2 chroma DC,
   8 chroma AC) is CAVLC-coded into a fixed layout of 34 ``(value, length)``
   codeword *slots* (length 0 = slot unused).  The per-block sequential
   pieces of ITU-T H.264 §9.2 — trailing-one detection, the adaptive
   ``suffixLength`` level loop, and the ``zerosLeft`` run_before loop — are
   fixed 16/15-step ``lax.scan``s whose state is vectorized over *all*
   blocks of the frame at once (~220k lanes at 1080p: ideal VPU shape).
   Nonzero coefficients are compacted into reverse scan order by a dense
   cumsum-rank one-hot reduction (argsort and in-scan gathers measured
   ~10x slower than dense selects on TPU).
2. nC contexts (§9.2.1) are pure neighbor shifts over the per-block
   total_coeff grids — no sequencing at all, because the slice-per-MB-row
   structure (ops/h264_device.py) removes cross-row dependencies.
3. Bits are concatenated scatter-free by the :mod:`.bitmerge` hierarchy:
   slots -> 256-bit block buffers -> 2048-bit MB buffers (dense mask
   reductions) -> per-row slice RBSPs (barrel-shift reduction tree).
   Pathological content that overflows the static block/MB caps sets a
   per-frame flag and the caller falls back to host entropy (never at
   sane qp; correctness is never silently lost).
4. Rows are compacted into one flat buffer by an output-sized gather, with
   a small metadata header prepended, so the host can fetch metadata +
   bitstream in a single bucketed pull, then only does emulation-prevention
   escaping + Annex-B NAL wrapping.

The pure-Python reference (bitstream/cavlc.py, bitstream/h264_entropy.py)
defines the contract; tests enforce byte-identical output.

Replaces the entropy half of NVENC (reference Dockerfile:210 selects
``nvh264enc``; SURVEY.md §7 "hard part #1" is exactly this stage).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..bitstream import cavlc as ref
from . import bitmerge

# ---------------------------------------------------------------------------
# Dense constant tables (padded to uniform shapes for device gathers)
# ---------------------------------------------------------------------------

_I32 = np.int32


def _build_ct_tables():
    """coeff_token as (5, 17, 4) length/bits arrays.

    Classes: 0..2 = VLC by nC range, 3 = nC>=8 six-bit FLC, 4 = chroma DC.
    """
    ln = np.zeros((5, 17, 4), _I32)
    bi = np.zeros((5, 17, 4), _I32)
    for cls in range(3):
        ln[cls] = np.asarray(ref._CT_LEN[cls], _I32).reshape(17, 4)
        bi[cls] = np.asarray(ref._CT_BITS[cls], _I32).reshape(17, 4)
    for tc in range(17):
        for t1 in range(min(tc, 3) + 1):
            l, b = ref._ct_flc(tc, t1)
            ln[3, tc, t1], bi[3, tc, t1] = l, b
    ln[4, :5] = np.asarray(ref._CT_LEN_CDC, _I32).reshape(5, 4)
    bi[4, :5] = np.asarray(ref._CT_BITS_CDC, _I32).reshape(5, 4)
    return ln, bi


def _build_tz_tables():
    """total_zeros: luma (16, 16) and chroma-DC (3, 4), [TotalCoeff-1][tz]."""
    ln = np.zeros((16, 16), _I32)
    bi = np.zeros((16, 16), _I32)
    for i, (lens, bits) in enumerate(zip(ref._TZ_LEN, ref._TZ_BITS)):
        ln[i, :len(lens)] = lens
        bi[i, :len(bits)] = bits
    lnc = np.zeros((3, 4), _I32)
    bic = np.zeros((3, 4), _I32)
    for i, (lens, bits) in enumerate(zip(ref._TZ_LEN_CDC, ref._TZ_BITS_CDC)):
        lnc[i, :len(lens)] = lens
        bic[i, :len(bits)] = bits
    return ln, bi, lnc, bic


def _build_rb_tables():
    """run_before: (7, 15) indexed [min(zerosLeft,7)-1][run]."""
    ln = np.zeros((7, 15), _I32)
    bi = np.zeros((7, 15), _I32)
    for i, (lens, bits) in enumerate(zip(ref._RB_LEN, ref._RB_BITS)):
        ln[i, :len(lens)] = lens
        bi[i, :len(bits)] = bits
    return ln, bi


_CT_LEN, _CT_BITS = _build_ct_tables()
_TZ_LEN, _TZ_BITS, _TZC_LEN, _TZC_BITS = _build_tz_tables()
_RB_LEN, _RB_BITS = _build_rb_tables()

# Packed (length << 16 | bits) variants: every VLC here has bits < 2^16
# and length <= 32, so one one-hot lookup recovers both — halving the
# dominant broadcast-compare cost of code_blocks (the 4K profile put the
# paired lookups at ~1/3 of the whole CAVLC slot stage).
def _pack_lb(len_tab, bits_tab):
    ln = np.asarray(len_tab, np.int64)
    bi = np.asarray(bits_tab, np.int64)
    assert (bi < (1 << 16)).all() and (ln <= 32).all()
    return ((ln << 16) | bi).astype(np.int32)


_CT_PACKED = _pack_lb(_CT_LEN, _CT_BITS)
_TZ_PACKED = _pack_lb(_TZ_LEN, _TZ_BITS)
_TZC_PACKED = _pack_lb(_TZC_LEN, _TZC_BITS)

# run_before packed table, shrunk to the 57 live entries: zerosLeft <= 6
# rows only reach run <= 6 (a zero-gap cannot exceed the zeros left), so
# rows 0..5 need 7 slots each and only the zl > 6 row needs all 15.
_RB_PACKED = np.zeros(57, np.int32)
for _row in range(6):
    for _run in range(7):
        _RB_PACKED[_row * 7 + _run] = int(
            _pack_lb(_RB_LEN[_row, _run], _RB_BITS[_row, _run]))
for _run in range(15):
    _RB_PACKED[42 + _run] = int(_pack_lb(_RB_LEN[6, _run],
                                         _RB_BITS[6, _run]))

# Exp-Golomb ue(v) as (value, length) for codeNum 0..63 — covers mb_type
# (<= 25) and coded_block_pattern codeNum (<= 47).
_UE_VAL = np.arange(1, 65, dtype=_I32)               # ue bit pattern = v+1
_UE_LEN = np.array([2 * int(v).bit_length() - 1 for v in _UE_VAL], _I32)

# bit_length table for the se(mb_qp_delta) slot (tune=hq): |delta| <= 51
# bounds the ue codeNum at 102, pattern codeNum+1 <= 103 < 256.
_SE_BITLEN = np.array([max(v, 1).bit_length() for v in range(256)], _I32)


def se_slots(v):
    """Vectorized signed Exp-Golomb: int32 array (|v| <= ~100) ->
    (value, length) slot arrays."""
    v = jnp.asarray(v, jnp.int32)
    code = jnp.where(v > 0, 2 * v - 1, -2 * v)       # ue codeNum
    pat = code + 1                                   # ue bit pattern
    n = jnp.asarray(_SE_BITLEN)[jnp.clip(pat, 0, 255)]
    return pat.astype(jnp.uint32), 2 * n - 1

# MB-syntax slot layout (stream order, spec 7.3.5):
#   [0]      mb_type
#   [1..16]  I_NxN per-block mode signaling (prev flag / 4-bit rem)
#   [17]     intra_chroma_pred_mode ue(0)
#   [18]     coded_block_pattern (I_NxN only; folded into mb_type for I16)
#   [19]     mb_qp_delta se(0) (absent for an I_NxN MB with cbp == 0)
MB_SYN_SLOTS = 20

# Number of (value, length) slots per coded block.
BLOCK_SLOTS = 1 + 1 + 16 + 1 + 15      # coeff_token, T1 signs, levels, tz, rb
MB_BLOCKS = 27                         # 1 lumaDC + 16 lumaAC + 2 cDC + 8 cAC

# Flat output layout: metadata words, then the compacted bitstream.
META_WORDS = 1024          # [0]=flags, [1]=total_words, [2:2+R]=row_bytes,
MAX_META_ROWS = 510        # [2+510:2+510+R]=row word offsets (8K = 270 rows ok)
FLAT_CAP_WORDS = 1 << 17   # 512 KiB bitstream cap (overflow flag if exceeded)


# ---------------------------------------------------------------------------
# Vectorized level VLC (§9.2.2.1) — single <=32-bit slot per level
# ---------------------------------------------------------------------------

def _level_vlc(code, sl):
    """(value, length) of one level codeword; vectorized.

    ``code`` is the levelCode (>=0), ``sl`` the current suffixLength.  All
    prefix-escape tiers up to level_prefix 17 are covered, bounding the
    codeword at 32 bits — sufficient for any level reachable from 8-bit
    residuals (|level| < 2^13; exercised by the qp=1 checkerboard test).
    """
    code = code.astype(jnp.int32)
    sl = sl.astype(jnp.int32)

    # sl == 0 tiers
    z_short_v = jnp.uint32(1)
    z_short_l = code + 1                                    # code < 14
    z_esc4_v = ((1 << 4) | (code - 14)).astype(jnp.uint32)  # 14 <= code < 30
    z_esc4_l = jnp.int32(19)                                # 15 + 4

    # sl > 0 regular tier
    prefix = code >> jnp.maximum(sl, 1)
    suffix_mask = (1 << jnp.maximum(sl, 1)) - 1
    r_v = ((1 << jnp.maximum(sl, 1)) | (code & suffix_mask)).astype(jnp.uint32)
    r_l = prefix + 1 + sl

    # common escape tiers; extra = 15 iff sl == 0
    extra = jnp.where(sl == 0, 15, 0)
    esc_base = (15 << sl) + extra
    e12_v = ((1 << 12) | (code - esc_base)).astype(jnp.uint32)  # prefix 15
    e12_l = jnp.int32(28)                                   # 16 + 12
    b16 = esc_base + (1 << 13) - 4096                       # prefix 16
    e13_v = ((1 << 13) | (code - b16)).astype(jnp.uint32)
    e13_l = jnp.int32(30)                                   # 17 + 13
    b17 = esc_base + (1 << 14) - 4096                       # prefix 17
    e14_v = ((1 << 14) | (code - b17)).astype(jnp.uint32)
    e14_l = jnp.int32(32)                                   # 18 + 14

    in_esc12 = code < esc_base + 4096
    in_esc13 = code < b16 + (1 << 13)
    esc_v = jnp.where(in_esc12, e12_v, jnp.where(in_esc13, e13_v, e14_v))
    esc_l = jnp.where(in_esc12, e12_l, jnp.where(in_esc13, e13_l, e14_l))

    v0 = jnp.where(code < 14, z_short_v,
                   jnp.where(code < 30, z_esc4_v, esc_v))
    l0 = jnp.where(code < 14, z_short_l,
                   jnp.where(code < 30, z_esc4_l, esc_l))
    vp = jnp.where(prefix < 15, r_v, esc_v)
    lp = jnp.where(prefix < 15, r_l, esc_l)

    value = jnp.where(sl == 0, v0, vp)
    length = jnp.where(sl == 0, l0, lp)
    return value.astype(jnp.uint32), length


# ---------------------------------------------------------------------------
# Block coder: levels -> 34 slots, vectorized over all blocks
# ---------------------------------------------------------------------------

def _onehot_lookup(table: np.ndarray, idx, active=None):
    """Small-table lookup as a dense one-hot select-reduce.

    A vectorized gather on TPU runs at ~130M elements/s (measured on v5e:
    1.7 ms per 220k-lane lookup — it was the single hottest op in this
    module's first profile, 15 of them inside the run_before scan).  A
    broadcast compare against the table index domain is pure VPU work that
    XLA fuses to ~nothing for tables this small (<= a few hundred entries).
    """
    flat = np.asarray(table).reshape(-1)
    n = flat.shape[0]
    ii = idx.astype(jnp.int32)[..., None]
    sel = ii == jnp.arange(n, dtype=jnp.int32)
    if active is not None:
        sel = sel & active[..., None]
    return jnp.where(sel, jnp.asarray(flat), 0).sum(axis=-1)


def code_blocks(levels, nc, is_cdc, max_coeff):
    """CAVLC-code N blocks at once.

    levels:    (N, 16) int32, scan order; entries >= ``max_coeff`` must be 0.
    nc:        (N,) int32 nC context (ignored where is_cdc).
    is_cdc:    (N,) bool — chroma-DC blocks (nC == -1 tables, maxNumCoeff 4).
    max_coeff: (N,) int32 in {4, 15, 16}.

    Returns (values, lengths): (N, 34) uint32 / int32 slot arrays.  The
    caller zeroes lengths of blocks that are not coded at all (cbp gating);
    a *coded* all-zero block correctly emits its 1-slot coeff_token here.
    """
    levels = levels.astype(jnp.int32)
    idx16 = jnp.arange(16, dtype=jnp.int32)

    mask = levels != 0
    csum = bitmerge.cumsum_mm(mask.astype(jnp.int32))
    total = csum[:, -1].astype(jnp.int32)                   # (N,)

    # Dense compaction into REVERSE scan order (highest frequency first):
    # nonzero i has rank csum[i]-1; its reverse index is total-1-rank.
    revj = jnp.where(mask, total[:, None] - csum, -1)       # (N, 16)
    onehot = revj[:, :, None] == idx16                      # (N, 16, 16)
    rev_vals = jnp.where(onehot, levels[:, :, None], 0).sum(axis=1)
    rev_pos = jnp.where(onehot, idx16[None, :, None], 0).sum(axis=1)
    # rev_vals[:, j] / rev_pos[:, j]: value/scan-pos of the j-th nonzero
    # counting back from the highest-frequency coefficient (j < total).

    # --- trailing ones (up to 3 final +-1s in scan order) ---
    v0, v1, v2 = rev_vals[:, 0], rev_vals[:, 1], rev_vals[:, 2]
    c0 = (total > 0) & (jnp.abs(v0) == 1)
    c1 = c0 & (total > 1) & (jnp.abs(v1) == 1)
    c2 = c1 & (total > 2) & (jnp.abs(v2) == 1)
    t1 = c0.astype(jnp.int32) + c1.astype(jnp.int32) + c2.astype(jnp.int32)

    # --- coeff_token ---
    cls = jnp.where(is_cdc, 4,
                    jnp.where(nc < 2, 0,
                              jnp.where(nc < 4, 1, jnp.where(nc < 8, 2, 3))))
    ct_idx = (cls * 17 + total) * 4 + t1
    ct_packed = _onehot_lookup(_CT_PACKED, ct_idx)
    ct_len = ct_packed >> 16
    ct_bits = (ct_packed & 0xFFFF).astype(jnp.uint32)

    # --- trailing-one signs, highest frequency first (one slot) ---
    s0 = (v0 < 0).astype(jnp.uint32)
    s1 = (v1 < 0).astype(jnp.uint32)
    s2 = (v2 < 0).astype(jnp.uint32)
    sign_val = jnp.where(t1 == 1, s0,
                         jnp.where(t1 == 2, (s0 << 1) | s1,
                                   (s0 << 2) | (s1 << 1) | s2)).astype(jnp.uint32)
    sign_val = jnp.where(t1 > 0, sign_val, 0)

    # --- remaining levels, highest frequency first (16-step scan) ---
    # The j-th emitted level is reverse-index (t1 + j); pre-shift the
    # reversed array by t1 (0..3) so the scan consumes plain xs slices.
    def shift_left(a, k):
        return jnp.pad(a[:, k:], ((0, 0), (0, k)))

    lv_in = rev_vals
    for k in (1, 2, 3):
        lv_in = jnp.where((t1 == k)[:, None], shift_left(rev_vals, k), lv_in)
    n_levels = total - t1
    sl_init = jnp.where((total > 10) & (t1 < 3), 1, 0).astype(jnp.int32)

    # Statically unrolled (16 fixed steps): as a ``lax.scan`` this loop
    # was the single hottest region of the 4K profile (~10 ms/frame of
    # the 46 ms step — per-iteration carry round trips through HBM);
    # unrolled, XLA fuses the 16 bodies into a handful of kernels.
    n = levels.shape[0]
    sl = sl_init
    first = jnp.ones((n,), bool)
    vals_steps, lens_steps = [], []
    for j in range(16):
        level = lv_in[:, j]
        active = j < n_levels
        code = jnp.where(level > 0, 2 * level - 2, -2 * level - 1)
        code = code - jnp.where(first & (t1 < 3), 2, 0)
        value, length = _level_vlc(code, sl)
        lens_steps.append(jnp.where(active, length, 0))
        vals_steps.append(jnp.where(active, value, 0))
        sl_new = jnp.maximum(sl, 1)
        sl_new = jnp.where(
            (jnp.abs(level) > (3 << jnp.maximum(sl_new - 1, 0)))
            & (sl_new < 6), sl_new + 1, sl_new)
        sl = jnp.where(active, sl_new, sl)
        first = first & ~active
    lv_vals = jnp.stack(vals_steps, axis=1)                 # (N, 16)
    lv_lens = jnp.stack(lens_steps, axis=1)

    # --- total_zeros ---
    tz = jnp.where(total > 0, rev_pos[:, 0] + 1 - total, 0)
    tzi = jnp.clip(total - 1, 0, 15)
    tzn_idx = tzi * 16 + jnp.clip(tz, 0, 15)
    tzc_idx = jnp.clip(tzi, 0, 2) * 4 + jnp.clip(tz, 0, 3)
    tz_packed = jnp.where(is_cdc,
                          _onehot_lookup(_TZC_PACKED, tzc_idx),
                          _onehot_lookup(_TZ_PACKED, tzn_idx))
    tz_len = tz_packed >> 16
    tz_bits = (tz_packed & 0xFFFF).astype(jnp.uint32)
    tz_emit = (total > 0) & (total < max_coeff)
    tz_len = jnp.where(tz_emit, tz_len, 0)
    tz_bits = jnp.where(tz_emit, tz_bits, 0)

    # --- run_before: NOT a loop, despite §9.2.3's sequential phrasing ---
    # run_before[k] is the zero-gap between consecutive nonzeros (a shifted
    # difference of scan positions) and zerosLeft[k] is tz minus the gaps
    # already emitted (an exclusive prefix sum) — both fully parallel.  The
    # first version of this module ran it as a 15-step lax.scan with two
    # per-step table gathers; the profiler put that scan at 36 ms of the
    # 67 ms 1080p frame (gathers, §_onehot_lookup).  This formulation is
    # byte-identical (the zerosLeft==0 early-out coincides with runs of 0:
    # once the zeros are spent, remaining gaps are empty) and costs ~nothing.
    rev_pos_next = shift_left(rev_pos, 1)
    k15 = jnp.arange(15, dtype=jnp.int32)
    run = jnp.clip(rev_pos[:, :15] - rev_pos_next[:, :15] - 1, 0, 14)
    zeros_left = tz[:, None] - bitmerge.cumsum_mm(run, inclusive=False)
    rb_active = (k15 <= (total - 2)[:, None]) & (zeros_left > 0)
    rb_row = jnp.clip(jnp.minimum(zeros_left, 7) - 1, 0, 6)
    # 57-entry packed domain: rows 0..5 hold run <= 6 (a gap can't
    # exceed the zeros left), the zl > 6 row holds run <= 14
    rb_idx = jnp.where(rb_row < 6,
                       rb_row * 7 + jnp.minimum(run, 6),
                       42 + run)
    rb_packed = _onehot_lookup(_RB_PACKED, rb_idx, active=rb_active)
    rb_lens = rb_packed >> 16
    rb_vals = (rb_packed & 0xFFFF).astype(jnp.uint32)

    values = jnp.concatenate([
        ct_bits[:, None], sign_val[:, None], lv_vals,
        tz_bits[:, None], rb_vals], axis=1)
    lengths = jnp.concatenate([
        ct_len[:, None], t1[:, None], lv_lens,
        tz_len[:, None], rb_lens], axis=1)
    return values.astype(jnp.uint32), lengths.astype(jnp.int32)


# ---------------------------------------------------------------------------
# nC context grids (§9.2.1), slice-per-row neighbor rules
# ---------------------------------------------------------------------------

def nc_grid(tc, left_from_prev_mb):
    """Vectorized nC for (R, C, B, B) per-block total_coeff grids.

    Mirrors bitstream/h264_entropy._nc_grid: the above-neighbor exists only
    within the MB (the MB above is in another slice); the left-neighbor
    crosses into the previous MB's rightmost block column.
    """
    na = jnp.zeros_like(tc)
    na_avail = jnp.zeros(tc.shape, bool)
    na = na.at[:, :, :, 1:].set(tc[:, :, :, :-1])
    na_avail = na_avail.at[:, :, :, 1:].set(True)
    na = na.at[:, 1:, :, 0].set(left_from_prev_mb[:, :-1])
    na_avail = na_avail.at[:, 1:, :, 0].set(True)
    nb = jnp.zeros_like(tc)
    nb_avail = jnp.zeros(tc.shape, bool)
    nb = nb.at[:, :, 1:, :].set(tc[:, :, :-1, :])
    nb_avail = nb_avail.at[:, :, 1:, :].set(True)
    both = na_avail & nb_avail
    return jnp.where(both, (na + nb + 1) >> 1,
                     jnp.where(na_avail, na,
                               jnp.where(nb_avail, nb, 0))).astype(jnp.int32)


# luma4x4BlkIdx -> (bx, by); must match ops.h264_device.LUMA_BLOCK_ORDER.
_BLK_X = np.array([0, 1, 0, 1, 2, 3, 2, 3, 0, 1, 0, 1, 2, 3, 2, 3], _I32)
_BLK_Y = np.array([0, 0, 1, 1, 0, 0, 1, 1, 2, 2, 3, 3, 2, 2, 3, 3], _I32)


def frame_block_slots(levels: dict, slice_qp: int = None):
    """Level tensors (ops/h264_device.encode_intra_frame) -> per-block slots.

    Handles mixed I_16x16 / I_NxN macroblocks (``mb_i4``): I_NxN luma
    blocks carry 16-coefficient levels (``luma_i4``) with per-8x8 cbp
    gating and no Hadamard DC block.  Returns (values, lengths, syn_vals,
    syn_lens, qp_sum): (R, C, 27, 34) codeword slots plus the (R, C, 20)
    MB-syntax slots (see MB_SYN_SLOTS layout); ``qp_sum`` is the summed
    per-MB effective qp (tune=hq; None otherwise) the host normalizes
    the rate model with.  ``slice_qp`` anchors the mb_qp_delta chain and
    is required when ``levels`` carries a ``qp_map``.
    """
    luma_dc = levels["luma_dc"]        # (R, C, 16) zigzag
    luma_ac = levels["luma_ac"]        # (R, C, 16, 15) blkIdx-ordered
    cb_dc = levels["cb_dc"]            # (R, C, 4)
    cb_ac = levels["cb_ac"]            # (R, C, 4, 15)
    cr_dc = levels["cr_dc"]
    cr_ac = levels["cr_ac"]
    nr, nc_mb = luma_dc.shape[:2]
    mb_i4 = jnp.asarray(levels.get(
        "mb_i4", np.zeros((nr, nc_mb), bool)))
    i4_modes = jnp.asarray(levels.get(
        "i4_modes", np.full((nr, nc_mb, 16), 2, np.int32)))
    luma_i4 = jnp.asarray(levels.get(
        "luma_i4", np.zeros((nr, nc_mb, 16, 16), np.int32)))

    cbp_luma = jnp.any(luma_ac != 0, axis=(2, 3))           # (R, C) I16
    grp_any = jnp.any(luma_i4.reshape(nr, nc_mb, 4, 4, 16) != 0,
                      axis=(3, 4))                          # (R, C, 4)
    cbp_luma4 = (grp_any.astype(jnp.int32)
                 * (1 << jnp.arange(4))).sum(axis=2)        # (R, C) I_NxN
    chroma_ac_any = (jnp.any(cb_ac != 0, axis=(2, 3))
                     | jnp.any(cr_ac != 0, axis=(2, 3)))
    chroma_dc_any = jnp.any(cb_dc != 0, axis=2) | jnp.any(cr_dc != 0, axis=2)
    cbp_chroma = jnp.where(chroma_ac_any, 2,
                           jnp.where(chroma_dc_any, 1, 0))  # (R, C)

    # --- per-block luma levels, gates and total_coeff grids ---
    grp_bit16 = grp_any[:, :, jnp.asarray(np.arange(16) // 4)]  # (R,C,16)
    luma_gate = jnp.where(mb_i4[:, :, None], grp_bit16,
                          cbp_luma[:, :, None])             # (R, C, 16)

    def pad16(a):
        """(..., k) -> (..., 16) zero-padded levels array."""
        k = a.shape[-1]
        return jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, 16 - k)])

    luma_lv = jnp.where(mb_i4[:, :, None, None], luma_i4,
                        pad16(luma_ac))                     # (R, C, 16, 16)

    tc_luma_blk = jnp.count_nonzero(luma_lv, axis=3).astype(jnp.int32)
    tc_luma_blk = tc_luma_blk * luma_gate
    tc_luma = jnp.zeros((nr, nc_mb, 4, 4), jnp.int32)
    tc_luma = tc_luma.at[:, :, jnp.asarray(_BLK_Y), jnp.asarray(_BLK_X)].set(
        tc_luma_blk)

    def chroma_tc(ac):
        t = jnp.count_nonzero(ac, axis=3).astype(jnp.int32)
        t = t * (cbp_chroma == 2)[:, :, None]
        return t.reshape(nr, nc_mb, 2, 2)

    tc_cb = chroma_tc(cb_ac)
    tc_cr = chroma_tc(cr_ac)

    ncl = nc_grid(tc_luma, tc_luma[:, :, :, 3])
    nccb = nc_grid(tc_cb, tc_cb[:, :, :, 1])
    nccr = nc_grid(tc_cr, tc_cr[:, :, :, 1])
    nc_dc = ncl[:, :, 0, 0]

    nmb = nr * nc_mb

    blk_levels = jnp.concatenate([
        pad16(luma_dc)[:, :, None, :],                      # lumaDC (I16)
        luma_lv,                                            # 16 luma blocks
        pad16(cb_dc)[:, :, None, :],                        # cbDC
        pad16(cr_dc)[:, :, None, :],                        # crDC
        pad16(cb_ac),                                       # 4 cbAC
        pad16(cr_ac),                                       # 4 crAC
    ], axis=2)                                              # (R, C, 27, 16)

    nc_luma_blk = ncl[:, :, jnp.asarray(_BLK_Y), jnp.asarray(_BLK_X)]
    nc_c = lambda g: g.reshape(nr, nc_mb, 4)
    blk_nc = jnp.concatenate([
        nc_dc[:, :, None], nc_luma_blk,
        jnp.zeros((nr, nc_mb, 2), jnp.int32),               # chroma DC: nC=-1
        nc_c(nccb), nc_c(nccr)], axis=2)                    # (R, C, 27)

    is_cdc = np.zeros(MB_BLOCKS, bool)
    is_cdc[17] = is_cdc[18] = True
    max_coeff = jnp.full((nr, nc_mb, MB_BLOCKS), 15, jnp.int32)
    max_coeff = max_coeff.at[:, :, 0].set(16)
    max_coeff = max_coeff.at[:, :, 17:19].set(4)
    max_coeff = max_coeff.at[:, :, 1:17].set(
        jnp.where(mb_i4[:, :, None], 16, 15))

    values, lengths = code_blocks(
        blk_levels.reshape(nmb * MB_BLOCKS, 16),
        blk_nc.reshape(-1),
        jnp.asarray(np.tile(is_cdc, nmb)),
        max_coeff.reshape(-1))
    values = values.reshape(nr, nc_mb, MB_BLOCKS, BLOCK_SLOTS)
    lengths = lengths.reshape(nr, nc_mb, MB_BLOCKS, BLOCK_SLOTS)

    # --- cbp gating: un-coded blocks emit nothing at all ---
    gate = jnp.ones((nr, nc_mb, MB_BLOCKS), bool)
    gate = gate.at[:, :, 0].set(~mb_i4)                     # no DC for I_NxN
    gate = gate.at[:, :, 1:17].set(luma_gate)
    gate = gate.at[:, :, 17:19].set((cbp_chroma > 0)[:, :, None])
    gate = gate.at[:, :, 19:27].set((cbp_chroma == 2)[:, :, None])
    lengths = lengths * gate[:, :, :, None]

    # tune=hq: per-MB mb_qp_delta chained from the slice qp per row
    # (ops/aq.qp_chain).  The syntax exists for every I16 MB and for
    # I_NxN with cbp != 0 — exactly the MBs that dequantize anything.
    qp_se = None
    qp_sum = None
    if "qp_map" in levels:
        from . import aq
        cbp_any = jnp.where(mb_i4, cbp_luma4 > 0, cbp_luma) \
            | (cbp_chroma > 0)
        codes = ~mb_i4 | cbp_any
        eff, delta = aq.qp_chain(levels["qp_map"], codes, int(slice_qp))
        sv, sl = se_slots(delta)
        qp_se = (sv, jnp.where(codes, sl, 0))
        qp_sum = jnp.sum(eff).astype(jnp.uint32)

    syn_vals, syn_lens = intra_mb_syntax_slots(
        levels["pred_mode"], mb_i4, i4_modes, cbp_luma, cbp_luma4,
        cbp_chroma, qp_se=qp_se)
    return values, lengths, syn_vals, syn_lens, qp_sum


def intra_mb_syntax_slots(pred_mode, mb_i4, i4_modes, cbp_luma, cbp_luma4,
                          cbp_chroma, qp_se=None):
    """Vectorized per-MB syntax slots (MB_SYN_SLOTS layout, spec 7.3.5).

    Mirrors bitstream/h264_entropy.encode_intra_picture's MB header
    emission, including the 8.3.1.1 min(A, B) Intra4x4PredMode predictor
    under slice-per-row neighbor rules.  ``qp_se`` (tune=hq): per-MB
    (value, length) override for the mb_qp_delta slot — lengths already
    gated to the MBs whose syntax carries it."""
    from ..bitstream.h264_entropy import _CBP_INTRA_TO_CODENUM

    nr, nc_mb = cbp_luma.shape
    mb_i4 = mb_i4.astype(bool)

    # raster-layout mode grid, 2 (DC) for non-I4 MBs
    modes_r = jnp.full((nr, nc_mb, 4, 4), 2, jnp.int32)
    modes_r = modes_r.at[:, :, jnp.asarray(_BLK_Y), jnp.asarray(_BLK_X)].set(
        jnp.where(mb_i4[:, :, None], i4_modes, 2))
    mode_a = jnp.full((nr, nc_mb, 4, 4), 2, jnp.int32)
    a_avail = jnp.zeros((nr, nc_mb, 4, 4), bool)
    mode_a = mode_a.at[:, :, :, 1:].set(modes_r[:, :, :, :-1])
    a_avail = a_avail.at[:, :, :, 1:].set(True)
    mode_a = mode_a.at[:, 1:, :, 0].set(modes_r[:, :-1, :, 3])
    a_avail = a_avail.at[:, 1:, :, 0].set(True)
    mode_b = jnp.full((nr, nc_mb, 4, 4), 2, jnp.int32)
    b_avail = jnp.zeros((nr, nc_mb, 4, 4), bool)
    mode_b = mode_b.at[:, :, 1:, :].set(modes_r[:, :, :-1, :])
    b_avail = b_avail.at[:, :, 1:, :].set(True)
    pred_i4 = jnp.where(a_avail & b_avail,
                        jnp.minimum(mode_a, mode_b), 2)     # (R, C, 4, 4)
    pred_blk = pred_i4[:, :, jnp.asarray(_BLK_Y), jnp.asarray(_BLK_X)]

    flag = i4_modes == pred_blk                             # (R, C, 16)
    rem = i4_modes - (i4_modes > pred_blk)
    mode_vals = jnp.where(flag, 1, rem).astype(jnp.uint32)
    mode_lens = jnp.where(mb_i4[:, :, None],
                          jnp.where(flag, 1, 4), 0)

    cl = cbp_luma.astype(jnp.int32)
    cc = cbp_chroma
    mbt16 = 1 + pred_mode + 4 * cc + 12 * cl                # codeNum, I16
    mbt_val = jnp.where(mb_i4, 1,
                        _onehot_lookup(_UE_VAL, mbt16)).astype(jnp.uint32)
    mbt_len = jnp.where(mb_i4, 1, _onehot_lookup(_UE_LEN, mbt16))

    cbp = cbp_luma4 + 16 * cc
    cbp_cn = _onehot_lookup(_CBP_INTRA_TO_CODENUM, cbp)
    cbp_val = _onehot_lookup(_UE_VAL, cbp_cn).astype(jnp.uint32)
    cbp_len = jnp.where(mb_i4, _onehot_lookup(_UE_LEN, cbp_cn), 0)

    chroma_val = jnp.ones((nr, nc_mb), jnp.uint32)          # ue(0)
    chroma_len = jnp.ones((nr, nc_mb), jnp.int32)
    if qp_se is None:
        qp_val = jnp.ones((nr, nc_mb), jnp.uint32)          # se(0)
        qp_len = jnp.where(mb_i4 & (cbp == 0), 0, 1)
    else:
        qp_val, qp_len = qp_se                              # tune=hq chain

    syn_vals = jnp.concatenate([
        mbt_val[:, :, None], mode_vals,
        chroma_val[:, :, None], cbp_val[:, :, None], qp_val[:, :, None]],
        axis=2)                                             # (R, C, 20)
    syn_lens = jnp.concatenate([
        mbt_len[:, :, None], mode_lens,
        chroma_len[:, :, None], cbp_len[:, :, None], qp_len[:, :, None]],
        axis=2)
    return syn_vals, syn_lens.astype(jnp.int32)


# ---------------------------------------------------------------------------
# Hierarchical packing: slots -> blocks -> MBs -> row RBSPs -> flat buffer
# ---------------------------------------------------------------------------

HDR_SLOTS = 3          # slice header bits, pre-encoded on host (<= 96 bits)

# Metadata word carrying the frame's summed per-MB effective qp
# (tune=hq; 0 = uniform slice qp).  Rows claim [2, 2+MAX_META_ROWS) and
# [2+MAX_META_ROWS, 2+2*MAX_META_ROWS); this sits just past them.
META_QP_SUM_WORD = 2 + 2 * MAX_META_ROWS          # = 1022 < META_WORDS


def pack_frame(values, lengths, syn_vals, syn_lens, hdr_vals, hdr_lens,
               qp_sum=None):
    """Scatter-free packing of a frame's CAVLC slots into row RBSPs.

    Returns (flat, overflow) where ``flat`` is a (META_WORDS*4 +
    FLAT_CAP_WORDS*4,) uint8 buffer: metadata words (flags, total words,
    per-row byte counts and word offsets) followed by the rows' RBSPs, each
    row starting at a 4-byte-aligned offset.  ``qp_sum`` (tune=hq) rides
    in META_QP_SUM_WORD so the host's rate controller can normalize by
    the mean coded qp without an extra device pull.
    """
    nr, nc_mb = syn_vals.shape[:2]

    # L1: each block's 34 slots -> 8-word buffer.
    blk_words, blk_bits, blk_ovf = bitmerge.slots_to_words(
        values, lengths, bitmerge.BLOCK_WORDS)              # (R,C,27,8)

    # MB syntax piece: 20 slots (<= ~80 bits) -> 8-word buffer.
    syn_words, syn_bits, syn_ovf = bitmerge.slots_to_words(
        syn_vals, syn_lens, bitmerge.BLOCK_WORDS)           # (R,C,8)

    # L2: 28 pieces -> 64-word MB buffer.
    pieces = jnp.concatenate([syn_words[:, :, None, :], blk_words], axis=2)
    piece_bits = jnp.concatenate([syn_bits[:, :, None], blk_bits], axis=2)
    mb_words, mb_bits, mb_ovf = bitmerge.merge_pieces_dense(
        pieces, piece_bits, bitmerge.MB_WORDS)              # (R, C, 64)

    # L3: 128 pieces (header + 120 MBs + trailing + padding) -> row RBSP.
    hdr_words4, hdr_bits, _ = bitmerge.slots_to_words(
        hdr_vals, hdr_lens, 4)                              # (R, 4)
    hdr_words = jnp.pad(hdr_words4, ((0, 0), (0, bitmerge.MB_WORDS - 4)))

    body_bits = hdr_bits + mb_bits.sum(axis=1)
    pad = (8 - ((body_bits + 1) % 8)) % 8
    # rbsp trailing: stop bit '1' + pad zeros; MSB-aligned that is always
    # 0x80000000 in word 0, only the *length* varies.
    trail_words = jnp.zeros((nr, bitmerge.MB_WORDS), jnp.uint32)
    trail_words = trail_words.at[:, 0].set(jnp.uint32(1) << 31)
    trail_bits = pad + 1

    n_pieces = 1 + nc_mb + 1
    p2 = 1 << int(np.ceil(np.log2(n_pieces)))
    row_pieces = jnp.concatenate([
        hdr_words[:, None, :], mb_words,
        trail_words[:, None, :],
        jnp.zeros((nr, p2 - n_pieces, bitmerge.MB_WORDS), jnp.uint32)], axis=1)
    row_bits_in = jnp.concatenate([
        hdr_bits[:, None], mb_bits, trail_bits[:, None],
        jnp.zeros((nr, p2 - n_pieces), jnp.int32)], axis=1)
    row_words_buf, row_bits = bitmerge.merge_pieces_tree(
        row_pieces, row_bits_in)                            # (R, p2*64)

    row_bytes = row_bits // 8                               # byte-aligned
    row_words = (row_bytes + 3) // 4
    word_off = jnp.cumsum(row_words) - row_words
    total_words = word_off[-1] + row_words[-1]

    # Output-sized gather compaction: flat word j belongs to row
    # r(j) = #\{rows whose span ends at or before j\}.
    word_cum = jnp.cumsum(row_words)                        # inclusive
    j = jnp.arange(FLAT_CAP_WORDS, dtype=jnp.int32)
    r = (j[:, None] >= word_cum[None, :]).sum(axis=1)
    rc = jnp.clip(r, 0, nr - 1)
    src = rc * row_words_buf.shape[1] + (j - word_off[rc])
    src = jnp.clip(src, 0, nr * row_words_buf.shape[1] - 1)
    flat_words = jnp.where(j < total_words,
                           row_words_buf.reshape(-1)[src], 0)

    overflow = (jnp.any(blk_ovf) | jnp.any(syn_ovf) | jnp.any(mb_ovf)
                | (total_words > FLAT_CAP_WORDS))

    assert nr <= MAX_META_ROWS, "metadata header row capacity exceeded"
    meta = jnp.zeros(META_WORDS, jnp.uint32)
    meta = meta.at[0].set(overflow.astype(jnp.uint32))
    meta = meta.at[1].set(total_words.astype(jnp.uint32))
    meta = meta.at[2:2 + nr].set(row_bytes.astype(jnp.uint32))
    meta = meta.at[2 + MAX_META_ROWS:2 + MAX_META_ROWS + nr].set(
        word_off.astype(jnp.uint32))
    if qp_sum is not None:
        meta = meta.at[META_QP_SUM_WORD].set(qp_sum.astype(jnp.uint32))

    allw = jnp.concatenate([meta, flat_words])
    flat = jnp.stack([(allw >> 24) & 0xFF, (allw >> 16) & 0xFF,
                      (allw >> 8) & 0xFF, allw & 0xFF],
                     axis=-1).reshape(-1).astype(jnp.uint8)
    return flat, overflow


# ---------------------------------------------------------------------------
# Fused frame encoder: RGB -> compacted CAVLC RBSP rows, one jit
# ---------------------------------------------------------------------------

@functools.partial(jax.jit,
                   static_argnames=("pad_h", "pad_w", "qp", "with_recon",
                                    "i16_modes", "tune"))
def encode_intra_cavlc_frame(rgb, hdr_vals, hdr_lens, pad_h: int, pad_w: int,
                             qp: int, with_recon: bool = False,
                             i16_modes: str = "auto", tune: str = "off",
                             next_y=None):
    """Full device stage: RGB frame -> flat metadata+bitstream buffer.

    The host's only per-frame pull is a bucketed prefix of ``flat``.
    """
    from . import h264_device

    levels = h264_device.encode_intra_frame.__wrapped__(
        rgb, pad_h, pad_w, qp, i16_modes, tune, next_y)
    return _finish_cavlc(levels, hdr_vals, hdr_lens, with_recon, qp)


@functools.partial(jax.jit,
                   static_argnames=("qp", "with_recon", "i16_modes",
                                    "tune"))
def encode_intra_cavlc_frame_yuv(y, cb, cr, hdr_vals, hdr_lens, qp: int,
                                 with_recon: bool = False,
                                 i16_modes: str = "auto",
                                 tune: str = "off", next_y=None):
    """Device stage from pre-converted YUV 4:2:0 planes (host cv2 color
    conversion halves the host->device bytes; see
    h264_device.encode_intra_frame_yuv)."""
    from . import h264_device

    levels = h264_device.encode_intra_frame_yuv.__wrapped__(
        y, cb, cr, qp, i16_modes, tune, next_y)
    return _finish_cavlc(levels, hdr_vals, hdr_lens, with_recon, qp)


def _finish_cavlc(levels, hdr_vals, hdr_lens, with_recon: bool,
                  slice_qp: int = None):
    recon = (levels["recon_y"], levels["recon_cb"], levels["recon_cr"])
    values, lengths, syn_vals, syn_lens, qp_sum = frame_block_slots(
        levels, slice_qp)
    flat, _ = pack_frame(values, lengths, syn_vals, syn_lens,
                         hdr_vals, hdr_lens, qp_sum=qp_sum)
    if with_recon:
        return flat, recon
    return flat


class FlatMeta:
    """Decoded metadata header of the flat buffer."""

    def __init__(self, meta_bytes: np.ndarray, nr: int):
        w = meta_bytes[:META_WORDS * 4].reshape(META_WORDS, 4).astype(np.uint32)
        words = (w[:, 0] << 24) | (w[:, 1] << 16) | (w[:, 2] << 8) | w[:, 3]
        self.overflow = bool(words[0])
        self.total_words = int(words[1])
        self.row_bytes = words[2:2 + nr].astype(np.int64)
        self.word_off = words[2 + MAX_META_ROWS:
                              2 + MAX_META_ROWS + nr].astype(np.int64)
        # tune=hq: summed per-MB effective qp (0 = uniform slice qp)
        self.qp_sum = int(words[META_QP_SUM_WORD])


def slice_header_slots(nr: int, nc_mb: int, *, frame_num: int,
                       idr_pic_id: int = 0, qp_delta: int = 0,
                       slice_type: int = 7, idr: bool = True,
                       deblocking_idc: int = 1):
    """Pre-encode every row's slice header into HDR_SLOTS (value, length)
    pairs (host side; tiny).  Returns (R, 3) uint32 values / int32 lengths.
    ``slice_type``/``idr`` default to the IDR I-slice; pass (5, False) for
    the P path."""
    from ..bitstream import h264 as syn
    from ..bitstream.bitwriter import BitWriter

    vals = np.zeros((nr, HDR_SLOTS), np.uint32)
    lens = np.zeros((nr, HDR_SLOTS), np.int32)
    for r in range(nr):
        bw = BitWriter()
        syn.slice_header(bw, first_mb=r * nc_mb, slice_type=slice_type,
                         frame_num=frame_num, idr=idr,
                         idr_pic_id=idr_pic_id, qp_delta=qp_delta,
                         deblocking_idc=deblocking_idc)
        bits, nbits = bw.peek_bits()
        assert nbits <= 32 * HDR_SLOTS, "slice header exceeds slot budget"
        # split MSB-first into 32-bit chunks, right-aligned per slot
        rem = nbits
        for s in range(HDR_SLOTS):
            take = min(32, rem)
            if take <= 0:
                break
            shift = rem - take
            vals[r, s] = (bits >> shift) & ((1 << take) - 1)
            lens[r, s] = take
            rem -= take
    return vals, lens


def assemble_annexb(flat_host: np.ndarray, meta: FlatMeta,
                    *, headers: bytes = b"", nal_type: int = None,
                    ref_idc: int = 3) -> bytes:
    """Host side: split the flat buffer into rows, EPB-escape each RBSP and
    wrap it in Annex-B NALs (IDR by default; (NAL_SLICE, 2) for P)."""
    from ..bitstream import h264 as syn

    if nal_type is None:
        nal_type = syn.NAL_IDR
    base = META_WORDS * 4
    out = bytearray(headers)
    for r in range(len(meta.row_bytes)):
        start = base + 4 * int(meta.word_off[r])
        rbsp = flat_host[start:start + int(meta.row_bytes[r])].tobytes()
        out += syn.nal_unit(nal_type, rbsp, ref_idc=ref_idc)
    return bytes(out)
