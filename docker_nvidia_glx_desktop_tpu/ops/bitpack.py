"""Parallel variable-length bit packing on TPU.

Entropy coding is nominally sequential — the classic argument for keeping it
on the host (SURVEY.md §7 hard part #1).  But *given* the codes, concatenating
variable-length codewords is a scan: an exclusive cumsum of code lengths gives
every codeword its absolute bit offset, and because the bit ranges are
disjoint, scatter-ADD into 32-bit words is equivalent to scatter-OR.  That
turns Huffman/VLC packing into two vectorized passes that XLA maps onto the
VPU, leaving only byte stuffing (and for H.264, emulation prevention) on the
host over the ~100x smaller packed output.

This matters doubly here: the host<->device link is the scarce resource (on
the dev tunnel it is ~10-20 MB/s device->host; on a real TPU VM PCIe is ~10
GB/s but a 4K60 stream still wants the 30x reduction), so the bitstream — not
the coefficient tensor — is what crosses the link.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pack_bits(values, lengths):
    """Concatenate variable-length codewords into a big-endian bit stream.

    values:  (N,) uint32 — right-aligned bit patterns (the codeword in the
             low ``lengths[i]`` bits; higher bits must be zero).
    lengths: (N,) int32 in [0, 32] — zero-length entries contribute nothing.

    Returns (packed_bytes, total_bits):
      packed_bytes: (ceil(maxbits/8),) uint8 device array, MSB-first; only
                    the first ceil(total_bits/8) bytes are meaningful and
                    trailing unused bits are 0.
      total_bits:   scalar int32 device array.
    """
    v = jnp.asarray(values, jnp.uint32)
    ln = jnp.asarray(lengths, jnp.int32)

    offsets = jnp.cumsum(ln) - ln                 # exclusive cumsum
    total_bits = offsets[-1] + ln[-1] if ln.shape[0] else jnp.int32(0)

    w = (offsets >> 5).astype(jnp.int32)          # word index
    s = (offsets & 31).astype(jnp.int32)          # bit offset in word
    end = s + ln                                   # in (0, 64]
    straddle = end > 32

    # High word: top bits of the codeword aligned at bit s.
    sh_hi = jnp.where(straddle, end - 32, 32 - end)
    hi = jnp.where(straddle,
                   v >> sh_hi.astype(jnp.uint32),
                   v << jnp.clip(sh_hi, 0, 31).astype(jnp.uint32))
    hi = jnp.where(ln > 0, hi, 0)

    # Low word: remaining (end - 32) bits, MSB-aligned.
    k = jnp.clip(end - 32, 0, 31)                 # bits in second word
    lo = (v << jnp.clip(32 - k, 0, 31).astype(jnp.uint32))
    lo = jnp.where(straddle, lo, 0)

    # Each entry is <= 32 bits, so N words + 1 (straddle spill) always fit.
    nwords = int(v.shape[0]) + 1
    words = jnp.zeros(nwords, jnp.uint32)
    words = words.at[w].add(hi, mode="drop")
    words = words.at[w + 1].add(lo, mode="drop")

    by = jnp.stack([(words >> 24) & 0xFF, (words >> 16) & 0xFF,
                    (words >> 8) & 0xFF, words & 0xFF], axis=-1)
    packed = by.reshape(-1).astype(jnp.uint8)
    return packed, total_bits


def finalize_bytes(packed_bytes, total_bits, pad_bit: int = 1) -> bytes:
    """Host-side: trim to total_bits, pad the final partial byte.

    ``packed_bytes``/``total_bits`` may be device arrays; this is the one
    host pull of the entropy stage.
    """
    import numpy as np
    nbits = int(total_bits)
    nbytes = (nbits + 7) // 8
    data = np.asarray(packed_bytes[:nbytes]).copy()
    rem = nbits % 8
    if rem and pad_bit:
        data[-1] |= (1 << (8 - rem)) - 1
    return data.tobytes()


def jpeg_stuff_bytes(data: bytes) -> bytes:
    """Insert 0x00 after every 0xFF (T.81 §B.1.1.5), vectorized on host."""
    import numpy as np
    arr = np.frombuffer(data, np.uint8)
    pos = np.nonzero(arr == 0xFF)[0]
    if len(pos) == 0:
        return data
    return np.insert(arr, pos + 1, 0).tobytes()
