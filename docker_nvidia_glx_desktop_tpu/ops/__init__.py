"""JAX/Pallas compute ops for the TPU encode path.

These replace the reference's GPU compute: NVENC's transform/quant silicon
and the NVRTC-JITted colorspace kernels (SURVEY.md §2.2 E1/E3).
"""

from .color import rgb_to_yuv420, yuv420_to_rgb, rgb_to_ycbcr, ycbcr_to_rgb  # noqa: F401
from .dct import (  # noqa: F401
    to_blocks, from_blocks, dct8x8, idct8x8, fdct4x4, idct4x4,
    hadamard4x4, hadamard2x2,
)
from .quant import (  # noqa: F401
    jpeg_quality_tables, jpeg_quantize, jpeg_dequantize,
    h264_quantize_4x4, h264_dequantize_4x4, chroma_qp,
)
from .scan import zigzag, unzigzag, ZIGZAG8, ZIGZAG4  # noqa: F401
