"""Device-side compaction of quantized level tensors — scatter-free.

The round-4 CABAC transport regression (VERDICT weak #4): serving with
``ENCODER_ENTROPY=cabac`` pulled the FULL dense level tensors to the
host every frame (~5.2M int32 coefficient slots at 1080p — the exact
multi-MB link cost device CAVLC was built to remove, see
ops/cavlc_device.py:1-8).  The obvious fix — cumsum + scatter of
(position, value) pairs — measured 50 ms/frame on v5e: TPU scatter
processes every one of the 5.2M updates regardless of sparsity.

This module instead encodes the levels as a variable-length bitstream
with the SAME scatter-free bitmerge pipeline the device CAVLC coder
uses (ops/bitmerge: dense mask-reduction slot packing, then log-depth
barrel-shift merge trees — all VPU work):

  slot code     zero coefficient -> 1 bit "0";
                nonzero          -> "1" + 15-bit two's-complement value
  L1            16 slots -> 8-word buffer (slots_to_words)
  L2            per-MB tree over the MB's 4x4 blocks
  L3            per-MB-row tree; rows then concatenated word-aligned by
                a fori_loop of dynamic_update_slice (contiguous copies)

Quantized desktop content is overwhelmingly zeros, so the payload is
~(0.97 + 0.5*density) bits/slot — ~0.7-2 MB/frame at 1080p vs 21 MB
dense.  Only ``HDR + row_words`` words cross the link (prefix-pulled
with the decaying-max guess machinery).  The host re-expands with the
threaded C decoder (native/levelpack.cpp, rows in parallel) or a
NumPy-per-row fallback, then feeds the native CABAC coder unchanged.

Values beyond +-16383 (impossible at serving qps, conceivable at qp<=4
on synthetic content) set the overflow flag; the caller falls back to
the dense pull — correctness never depends on the encoding.

Transport layout (uint32 words):
  [0] version (1)   [1] value-overflow flag   [2] total payload words
  [3] rows R        [4] slots per row         [5..7] reserved
  [META_WORDS .. META_WORDS+R)   per-row payload word counts
  [META_WORDS+R ..)              row payloads, each word-aligned
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import bitmerge

__all__ = ["META_WORDS", "INTRA_KEYS", "P_KEYS", "pack_levels",
           "header_words", "payload_words", "unpack_levels"]

META_WORDS = 8

# Per-MB slot layout: (key, slots, final dense shape per MB).  The order
# is the wire contract between the device packer and the host decoder.
INTRA_KEYS = (
    ("luma_dc", 16, (16,)),
    ("luma_ac", 240, (16, 15)),
    ("cb_dc", 4, (4,)),
    ("cb_ac", 60, (4, 15)),
    ("cr_dc", 4, (4,)),
    ("cr_ac", 60, (4, 15)),
    ("luma_i4", 256, (16, 16)),
)
P_KEYS = (
    ("luma", 256, (16, 16)),
    ("cb_dc", 4, (4,)),
    ("cb_ac", 60, (4, 15)),
    ("cr_dc", 4, (4,)),
    ("cr_ac", 60, (4, 15)),
)


def _mb_slots(levels: dict, keys) -> jax.Array:
    """(R, C, S) slot matrix in wire order."""
    r, c = levels[keys[0][0]].shape[:2]
    parts = [levels[k].reshape(r, c, -1).astype(jnp.int32)
             for k, _, _ in keys]
    return jnp.concatenate(parts, axis=-1)


@jax.jit
def _pack(slots3: jax.Array) -> jax.Array:
    r, c, s = slots3.shape
    assert s % 16 == 0
    nb = s // 16
    v = slots3
    nz = v != 0
    overflow = ((v > 16383) | (v < -16384)).any()
    val = jnp.where(nz, (1 << 15) | (v & 0x7FFF), 0).astype(jnp.uint32)
    ln = jnp.where(nz, 16, 1).astype(jnp.int32)
    # L1: 16 slots -> 8 words (max 16*16 = 256 bits exactly)
    w1, nb1, _ = bitmerge.slots_to_words(
        val.reshape(r, c, nb, 16), ln.reshape(r, c, nb, 16), 8)
    # L2: per-MB tree over the blocks
    p2 = 1 << int(np.ceil(np.log2(nb)))
    w1 = jnp.pad(w1, ((0, 0), (0, 0), (0, p2 - nb), (0, 0)))
    nb1 = jnp.pad(nb1, ((0, 0), (0, 0), (0, p2 - nb)))
    w2, mb_bits = bitmerge.merge_pieces_tree(w1, nb1)       # (r, c, p2*8)
    mb_cap = s * 16 // 32                                   # exact max
    w2 = w2[..., :mb_cap]
    # L3: per-row tree over the MBs
    c2 = 1 << int(np.ceil(np.log2(c)))
    w2 = jnp.pad(w2, ((0, 0), (0, c2 - c), (0, 0)))
    mb_bits = jnp.pad(mb_bits, ((0, 0), (0, c2 - c)))
    w3, row_bits = bitmerge.merge_pieces_tree(w2, mb_bits)  # (r, c2*cap)
    row_words = ((row_bits + 31) >> 5).astype(jnp.int32)
    row_cap = w3.shape[-1]

    hdr = jnp.zeros(META_WORDS + r, jnp.uint32)
    hdr = (hdr.at[0].set(1)
           .at[1].set(overflow.astype(jnp.uint32))
           .at[2].set(row_words.sum().astype(jnp.uint32))
           .at[3].set(r).at[4].set(s)
           .at[META_WORDS:].set(row_words.astype(jnp.uint32)))
    offs = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(row_words)])[:r]
    payload = jnp.zeros(r * row_cap, jnp.uint32)

    def body(i, acc):
        # rows are written in ascending-offset order, so row i+1's write
        # reclaims row i's zero-padded tail; payloads never overlap
        return jax.lax.dynamic_update_slice(
            acc, jax.lax.dynamic_index_in_dim(w3, i, keepdims=False),
            (offs[i],))

    payload = jax.lax.fori_loop(0, r, body, payload)
    return jnp.concatenate([hdr, payload])


def pack_levels(levels: dict, keys) -> jax.Array:
    """Compact the level tensors named by ``keys`` (INTRA_KEYS/P_KEYS)
    into one uint32 transport buffer (device computation, no sync)."""
    return _pack(_mb_slots(levels, keys))


def header_words(rows: int) -> int:
    return META_WORDS + rows


def payload_words(head: np.ndarray) -> int:
    """Total payload words, from a pulled header prefix."""
    return int(head[2])


# ---------------------------------------------------------------------------
# Host-side decode
# ---------------------------------------------------------------------------

def _unpack_rows_numpy(payload: np.ndarray, row_off: np.ndarray,
                       rows: int, slots_row: int) -> np.ndarray:
    """Row-wise bit decode without the native library.  Vectorized over
    the row's bits (one pass per row); fine for tests and small
    geometries — serving uses the C decoder."""
    out = np.zeros(rows * slots_row, np.int32)
    for r in range(rows):
        w = payload[row_off[r]:row_off[r + 1]]
        if w.size == 0:
            continue
        bits = np.unpackbits(
            np.ascontiguousarray(w.astype(">u4")).view(np.uint8))
        pos = 0
        base = r * slots_row
        for s in range(slots_row):
            if bits[pos]:
                raw = 0
                for b in bits[pos + 1:pos + 16]:
                    raw = (raw << 1) | int(b)
                out[base + s] = raw - (raw >> 14) * (1 << 15)
                pos += 16
            else:
                pos += 1
    return out


def unpack_levels(buf: np.ndarray, rows: int, cols: int, keys):
    """Expand a transport buffer (host array covering header + payload)
    back into the dense per-tensor arrays, or None on value overflow."""
    head = buf[:META_WORDS + rows]
    assert int(head[0]) == 1, "level_pack version mismatch"
    if int(head[1]):
        return None
    slots_row = cols * int(head[4])
    row_words = head[META_WORDS:META_WORDS + rows].astype(np.int64)
    row_off = np.zeros(rows + 1, np.int64)
    np.cumsum(row_words, out=row_off[1:])
    payload = np.ascontiguousarray(
        buf[META_WORDS + rows:META_WORDS + rows + int(row_off[-1])],
        dtype=np.uint32)
    from ..native import lib as native_lib
    dense = None
    if native_lib.has_level_unpack():
        dense = native_lib.level_unpack(payload, row_off, rows, slots_row)
    if dense is None:
        dense = _unpack_rows_numpy(payload, row_off, rows, slots_row)
    dense = dense.reshape(rows, cols, int(head[4]))
    out, off = {}, 0
    for k, n, shape in keys:
        out[k] = np.ascontiguousarray(
            dense[:, :, off:off + n]).reshape((rows, cols) + shape)
        off += n
    return out
