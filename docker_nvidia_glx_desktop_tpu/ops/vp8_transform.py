"""VP8 4x4 transforms + quantization (RFC 6386 §14), vectorized numpy.

The *inverse* transforms are the normative ones — the encoder's
reconstruction loop must match the (libvpx) decoder bit-exactly, which
the golden round-trip tests assert.  The forward transforms only shape
quality, but follow the reference implementation's integer versions so
coefficients land in the ranges the token tables expect.

All functions operate on batches: ``blocks`` is (N, 4, 4) int32.
Reference for the spec constants: cospi8sqrt2minus1=20091,
sinpi8sqrt2=35468 (Q16 fixed point).
"""

from __future__ import annotations

import numpy as np

__all__ = ["fdct4x4", "idct4x4", "fwht4x4", "iwht4x4",
           "quant_factors", "quantize", "dequantize"]

_C1 = 20091          # cospi8sqrt2 - 1 in Q16
_S1 = 35468          # sinpi8sqrt2 in Q16


def fdct4x4(blocks: np.ndarray) -> np.ndarray:
    """Forward DCT (reference integer version), (N,4,4) -> (N,4,4)."""
    ip = blocks.astype(np.int64)
    # rows
    a1 = (ip[:, :, 0] + ip[:, :, 3]) * 8
    b1 = (ip[:, :, 1] + ip[:, :, 2]) * 8
    c1 = (ip[:, :, 1] - ip[:, :, 2]) * 8
    d1 = (ip[:, :, 0] - ip[:, :, 3]) * 8
    t = np.empty_like(ip)
    t[:, :, 0] = a1 + b1
    t[:, :, 2] = a1 - b1
    t[:, :, 1] = (c1 * 2217 + d1 * 5352 + 14500) >> 12
    t[:, :, 3] = (d1 * 2217 - c1 * 5352 + 7500) >> 12
    # columns
    a1 = t[:, 0] + t[:, 3]
    b1 = t[:, 1] + t[:, 2]
    c1 = t[:, 1] - t[:, 2]
    d1 = t[:, 0] - t[:, 3]
    out = np.empty_like(ip)
    out[:, 0] = (a1 + b1 + 7) >> 4
    out[:, 2] = (a1 - b1 + 7) >> 4
    out[:, 1] = ((c1 * 2217 + d1 * 5352 + 12000) >> 16) + (d1 != 0)
    out[:, 3] = (d1 * 2217 - c1 * 5352 + 51000) >> 16
    return out.astype(np.int32)


def idct4x4(blocks: np.ndarray) -> np.ndarray:
    """Normative inverse DCT (§14.3), (N,4,4) -> (N,4,4) residual."""
    ip = blocks.astype(np.int64)
    # columns
    a1 = ip[:, 0] + ip[:, 2]
    b1 = ip[:, 0] - ip[:, 2]
    t1 = (ip[:, 1] * _S1) >> 16
    t2 = ip[:, 3] + ((ip[:, 3] * _C1) >> 16)
    c1 = t1 - t2
    t1 = ip[:, 1] + ((ip[:, 1] * _C1) >> 16)
    t2 = (ip[:, 3] * _S1) >> 16
    d1 = t1 + t2
    t = np.empty_like(ip)
    t[:, 0] = a1 + d1
    t[:, 3] = a1 - d1
    t[:, 1] = b1 + c1
    t[:, 2] = b1 - c1
    # rows
    a1 = t[:, :, 0] + t[:, :, 2]
    b1 = t[:, :, 0] - t[:, :, 2]
    t1 = (t[:, :, 1] * _S1) >> 16
    t2 = t[:, :, 3] + ((t[:, :, 3] * _C1) >> 16)
    c1 = t1 - t2
    t1 = t[:, :, 1] + ((t[:, :, 1] * _C1) >> 16)
    t2 = (t[:, :, 3] * _S1) >> 16
    d1 = t1 + t2
    out = np.empty_like(ip)
    out[:, :, 0] = (a1 + d1 + 4) >> 3
    out[:, :, 3] = (a1 - d1 + 4) >> 3
    out[:, :, 1] = (b1 + c1 + 4) >> 3
    out[:, :, 2] = (b1 - c1 + 4) >> 3
    return out.astype(np.int32)


def fwht4x4(blocks: np.ndarray) -> np.ndarray:
    """Forward Walsh-Hadamard for the Y2 (luma DC) block."""
    ip = blocks.astype(np.int64)
    a1 = (ip[:, :, 0] + ip[:, :, 2]) * 4
    d1 = (ip[:, :, 1] + ip[:, :, 3]) * 4
    c1 = (ip[:, :, 1] - ip[:, :, 3]) * 4
    b1 = (ip[:, :, 0] - ip[:, :, 2]) * 4
    t = np.empty_like(ip)
    t[:, :, 0] = a1 + d1 + (a1 != 0)
    t[:, :, 1] = b1 + c1
    t[:, :, 2] = b1 - c1
    t[:, :, 3] = a1 - d1
    a1 = t[:, 0] + t[:, 2]
    d1 = t[:, 1] + t[:, 3]
    c1 = t[:, 1] - t[:, 3]
    b1 = t[:, 0] - t[:, 2]
    a2 = a1 + d1
    b2 = b1 + c1
    c2 = b1 - c1
    d2 = a1 - d1
    a2 += a2 < 0
    b2 += b2 < 0
    c2 += c2 < 0
    d2 += d2 < 0
    out = np.empty_like(ip)
    out[:, 0] = (a2 + 3) >> 3
    out[:, 1] = (b2 + 3) >> 3
    out[:, 2] = (c2 + 3) >> 3
    out[:, 3] = (d2 + 3) >> 3
    return out.astype(np.int32)


def iwht4x4(blocks: np.ndarray) -> np.ndarray:
    """Normative inverse WHT (§14.3): Y2 -> 16 luma DC values."""
    ip = blocks.astype(np.int64)
    a1 = ip[:, 0] + ip[:, 3]
    b1 = ip[:, 1] + ip[:, 2]
    c1 = ip[:, 1] - ip[:, 2]
    d1 = ip[:, 0] - ip[:, 3]
    t = np.empty_like(ip)
    t[:, 0] = a1 + b1
    t[:, 1] = c1 + d1
    t[:, 2] = a1 - b1
    t[:, 3] = d1 - c1
    a1 = t[:, :, 0] + t[:, :, 3]
    b1 = t[:, :, 1] + t[:, :, 2]
    c1 = t[:, :, 1] - t[:, :, 2]
    d1 = t[:, :, 0] - t[:, :, 3]
    out = np.empty_like(ip)
    out[:, :, 0] = (a1 + b1 + 3) >> 3
    out[:, :, 1] = (c1 + d1 + 3) >> 3
    out[:, :, 2] = (a1 - b1 + 3) >> 3
    out[:, :, 3] = (d1 - c1 + 3) >> 3
    return out.astype(np.int32)


def quant_factors(qi: int, tables) -> dict:
    """Per-plane (dc, ac) dequant factors for quant index ``qi``
    (§9.6 / §14.1 derivations, zero deltas)."""
    qi = int(np.clip(qi, 0, 127))
    dcq = int(tables.dc_qlookup[qi])
    acq = int(tables.ac_qlookup[qi])
    return {
        "y1": (dcq, acq),
        "y2": (dcq * 2, max((acq * 155) // 100, 8)),
        "uv": (min(dcq, 132), acq),
    }


def quantize(coeffs: np.ndarray, dc_q: int, ac_q: int) -> np.ndarray:
    """Toward-zero division; coeff[0,0] uses dc_q, the rest ac_q."""
    q = np.full((4, 4), ac_q, np.int64)
    q[0, 0] = dc_q
    c = coeffs.astype(np.int64)
    return (np.sign(c) * (np.abs(c) // q)).astype(np.int32)


def dequantize(qcoeffs: np.ndarray, dc_q: int, ac_q: int) -> np.ndarray:
    q = np.full((4, 4), ac_q, np.int64)
    q[0, 0] = dc_q
    return (qcoeffs.astype(np.int64) * q).astype(np.int32)
