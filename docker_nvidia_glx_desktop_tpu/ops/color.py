"""RGB <-> YCbCr 4:2:0 color conversion as JAX ops.

Replaces the reference's GPU colorspace stage (``videoconvert``/``cudaconvert``
RGBx->NV12, NVRTC-JITted; reference Dockerfile:469-470, SURVEY.md §3.2).  On
TPU this is a fused elementwise pass over the frame: a (H, W, 3) uint8 frame
becomes Y (H, W) + subsampled Cb/Cr (H/2, W/2).  XLA fuses the 3x3 color
matrix, offset, and 2x2 chroma averaging into the surrounding pipeline, so no
hand-written kernel is needed for this stage.

Two matrix conventions:

- ``"full"``  — JPEG/JFIF full-range BT.601 (used by the MJPEG codec).
- ``"video"`` — studio-range BT.601 (16..235 luma), the default assumption of
  H.264/VP8 decoders when no VUI/colorspace info is signaled.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# BT.601 luma coefficients.
_KR, _KG, _KB = 0.299, 0.587, 0.114

# Full-range (JFIF) RGB -> YCbCr.
_M_FULL = np.array(
    [
        [_KR, _KG, _KB],
        [-_KR / 1.772, -_KG / 1.772, 0.5],
        [0.5, -_KG / 1.402, -_KB / 1.402],
    ],
    dtype=np.float32,
)
_OFF_FULL = np.array([0.0, 128.0, 128.0], dtype=np.float32)

# Studio-range: Y in [16, 235], C in [16, 240].
_M_VIDEO = _M_FULL * np.array([[219.0 / 255.0], [224.0 / 255.0], [224.0 / 255.0]], dtype=np.float32)
_OFF_VIDEO = np.array([16.0, 128.0, 128.0], dtype=np.float32)


def rgb_to_ycbcr(rgb, matrix: str = "video"):
    """Convert an (..., H, W, 3) RGB array to (..., H, W, 3) YCbCr, float32.

    No subsampling; values are *not* rounded so downstream transforms keep
    full precision until quantization.
    """
    m, off = (_M_FULL, _OFF_FULL) if matrix == "full" else (_M_VIDEO, _OFF_VIDEO)
    rgb_f = jnp.asarray(rgb).astype(jnp.float32)
    # Explicit multiply-adds (not a matmul): keeps full f32 precision on every
    # backend and lowers to fused VPU ops rather than a degenerate K=3 MXU op.
    chans = [
        rgb_f[..., 0] * m[d][0] + rgb_f[..., 1] * m[d][1]
        + rgb_f[..., 2] * m[d][2] + off[d]
        for d in range(3)
    ]
    return jnp.stack(chans, axis=-1)


def ycbcr_to_rgb(ycc, matrix: str = "video"):
    """Inverse of :func:`rgb_to_ycbcr`; returns float32 (caller clips/rounds)."""
    m, off = (_M_FULL, _OFF_FULL) if matrix == "full" else (_M_VIDEO, _OFF_VIDEO)
    m_inv = np.linalg.inv(m.astype(np.float64)).astype(np.float32)
    ycc_f = jnp.asarray(ycc).astype(jnp.float32)
    ch = [ycc_f[..., d] - off[d] for d in range(3)]
    chans = [
        ch[0] * m_inv[d][0] + ch[1] * m_inv[d][1] + ch[2] * m_inv[d][2]
        for d in range(3)
    ]
    return jnp.stack(chans, axis=-1)


def subsample_420(chroma):
    """2x2 mean-pool one chroma plane (..., H, W) -> (..., H/2, W/2).

    H and W must be even (callers pad frames to macroblock multiples first).
    """
    c = jnp.asarray(chroma)
    h, w = c.shape[-2], c.shape[-1]
    c4 = c.reshape(c.shape[:-2] + (h // 2, 2, w // 2, 2))
    return c4.mean(axis=(-3, -1))


def upsample_420(chroma):
    """Nearest-neighbour upsample (..., H/2, W/2) -> (..., H, W)."""
    c = jnp.asarray(chroma)
    c = jnp.repeat(c, 2, axis=-2)
    return jnp.repeat(c, 2, axis=-1)


def rgb_to_yuv420(rgb, matrix: str = "video"):
    """Full pipeline: (..., H, W, 3) uint8 RGB -> (Y, Cb, Cr) planes.

    Y is (..., H, W); Cb/Cr are (..., H/2, W/2).  All float32, unrounded.
    """
    ycc = rgb_to_ycbcr(rgb, matrix=matrix)
    y = ycc[..., 0]
    cb = subsample_420(ycc[..., 1])
    cr = subsample_420(ycc[..., 2])
    return y, cb, cr


def yuv420_to_rgb(y, cb, cr, matrix: str = "video"):
    """Inverse pipeline for tests/round-trips; returns uint8 RGB."""
    ycc = jnp.stack([jnp.asarray(y), upsample_420(cb), upsample_420(cr)], axis=-1)
    rgb = ycbcr_to_rgb(ycc, matrix=matrix)
    return jnp.clip(jnp.round(rgb), 0, 255).astype(jnp.uint8)
