"""Device-side JPEG entropy stage: symbols, histograms, and bit packing.

The classic encoder pulls the quantized coefficients to the host and runs
Huffman coding there.  On TPU the coefficient tensor is ~30x larger than the
packed scan, and the host link is the scarce resource — so everything except
table construction (a <300-symbol problem) and byte stuffing runs on device:

  pass 1 (jit): zigzag coeffs -> run/size symbols -> DC/AC histograms
                (only ~2 KB of histograms crosses to the host)
  host:         optimal Huffman tables from the histograms (Annex K.2)
  pass 2 (jit): gather codes for every symbol -> parallel bit pack
                (:func:`..ops.bitpack.pack_bits`) -> packed scan bytes

Symbol layout per 8x8 block: [DC] + 63 x [ZRL, ZRL, ZRL, symbol] + [EOB]
= 254 fixed entry slots; absent symbols get length 0 and vanish in the pack.
A gap of z zeros before a coefficient needs floor(z/16) <= 3 ZRL codes, so
three slots are always enough.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .bitpack import pack_bits

ENTRIES_PER_BLOCK = 1 + 63 * 4 + 1  # DC + (3 ZRL + sym) per AC pos + EOB


def uniform_dense_tables(as_jnp: bool = True):
    """Shape-compatible uniform code books for dry runs / compile checks.

    Matches the (codes uint32, lens int32) x (dc_l, ac_l, dc_c, ac_c)
    argument order of :func:`jpeg_pack`.  Not optimal codes — only for
    exercising the pack path without a histogram pass.
    """
    import numpy as np
    xp = jnp if as_jnp else np
    out = []
    for n in (17, 256, 17, 256):
        out.extend([xp.arange(n, dtype=xp.uint32),
                    xp.full(n, (n - 1).bit_length(), xp.int32)])
    return out


def _bit_length(av):
    """Number of bits of |v| (exact for av < 2^24 via float32 log2)."""
    avf = jnp.maximum(av, 1).astype(jnp.float32)
    return jnp.where(av > 0,
                     jnp.floor(jnp.log2(avf)).astype(jnp.int32) + 1,
                     0)


def _amplitude(v, size):
    """JPEG one's-complement amplitude bits of v (size = bit length)."""
    return jnp.where(v >= 0, v, v + (jnp.left_shift(1, size) - 1)).astype(jnp.uint32)


def component_symbols(zz):
    """Vectorized symbol extraction for one component.

    zz: (nblk, 64) int32 zigzagged quantized coefficients in scan order.
    Returns dict of per-block symbol tensors (see keys below).
    """
    zz = jnp.asarray(zz, jnp.int32)
    dc = zz[:, 0]
    diff = dc - jnp.concatenate([jnp.zeros(1, jnp.int32), dc[:-1]])
    dc_size = _bit_length(jnp.abs(diff))
    dc_amp = _amplitude(diff, dc_size)

    ac = zz[:, 1:]                                    # (nblk, 63)
    m = ac != 0
    pos = jnp.arange(1, 64, dtype=jnp.int32)[None, :]  # (1, 63)
    nz_pos = jnp.where(m, pos, 0)
    last_nz = jnp.max(nz_pos, axis=1)                 # (nblk,), 0 if none
    # prev_nz[k] = position of previous nonzero before k (0 => DC slot)
    cm = jax.lax.cummax(nz_pos, axis=1)
    prev_nz = jnp.concatenate(
        [jnp.zeros((zz.shape[0], 1), jnp.int32), cm[:, :-1]], axis=1)
    gap = pos - prev_nz - 1                           # zeros since last nonzero
    run = jnp.where(m, gap % 16, 0)
    nzrl = jnp.where(m, gap // 16, 0)                 # 0..3 ZRLs before symbol
    ac_size = _bit_length(jnp.abs(ac))
    sym = jnp.where(m, (run << 4) | ac_size, 0)
    ac_amp = _amplitude(ac, ac_size)
    eob = last_nz < 63
    return {
        "dc_size": dc_size, "dc_amp": dc_amp,
        "mask": m, "sym": sym, "amp": ac_amp, "size": ac_size, "nzrl": nzrl,
        "eob": eob,
    }


def component_histogram(sy):
    """DC (17-bin) and AC (256-bin) histograms from component_symbols output."""
    dc_hist = jnp.zeros(17, jnp.int32).at[sy["dc_size"]].add(1)
    ac_hist = jnp.zeros(256, jnp.int32)
    # masked-off positions carry sym 0 but add False (0), so bin 0 stays clean
    ac_hist = ac_hist.at[sy["sym"].reshape(-1)].add(sy["mask"].reshape(-1))
    ac_hist = ac_hist.at[0xF0].add(jnp.sum(sy["nzrl"]))
    ac_hist = ac_hist.at[0x00].add(jnp.sum(sy["eob"]))
    return dc_hist, ac_hist


def component_entries(sy, dc_codes, dc_lens, ac_codes, ac_lens):
    """(value, length) entry tensors for one component, (nblk, 254)."""
    nblk = sy["dc_size"].shape[0]

    dc_code = dc_codes[sy["dc_size"]]
    dc_len = dc_lens[sy["dc_size"]]
    dc_val = (dc_code << sy["dc_size"].astype(jnp.uint32)) | sy["dc_amp"]
    dc_vlen = dc_len + sy["dc_size"]

    zrl_code = ac_codes[0xF0]
    zrl_len = ac_lens[0xF0]
    # slots j = 0..2: present when nzrl > j
    zrl_vals = jnp.broadcast_to(zrl_code, (nblk, 63, 3)).astype(jnp.uint32)
    zrl_lens = jnp.where(
        sy["nzrl"][..., None] > jnp.arange(3, dtype=jnp.int32), zrl_len, 0)

    s_code = ac_codes[sy["sym"]]
    s_len = ac_lens[sy["sym"]]
    s_val = (s_code << sy["size"].astype(jnp.uint32)) | sy["amp"]
    s_vlen = jnp.where(sy["mask"], s_len + sy["size"], 0)

    ac_vals = jnp.concatenate([zrl_vals, s_val[..., None]], axis=-1)   # (nblk,63,4)
    ac_vlens = jnp.concatenate([zrl_lens, s_vlen[..., None]], axis=-1)

    eob_val = jnp.broadcast_to(ac_codes[0], (nblk,)).astype(jnp.uint32)
    eob_len = jnp.where(sy["eob"], ac_lens[0], 0)

    vals = jnp.concatenate(
        [dc_val[:, None], ac_vals.reshape(nblk, 63 * 4), eob_val[:, None]],
        axis=1)
    lens = jnp.concatenate(
        [dc_vlen[:, None], ac_vlens.reshape(nblk, 63 * 4), eob_len[:, None]],
        axis=1)
    return vals, lens


@jax.jit
def jpeg_analyze(y_flat, cb, cr):
    """Pass 1: histograms per table id.  Only these cross to the host."""
    sy_y = component_symbols(y_flat)
    sy_cb = component_symbols(cb)
    sy_cr = component_symbols(cr)
    dc_y, ac_y = component_histogram(sy_y)
    dc_b, ac_b = component_histogram(sy_cb)
    dc_r, ac_r = component_histogram(sy_cr)
    return dc_y, ac_y, dc_b + dc_r, ac_b + ac_r


@jax.jit
def jpeg_pack(y_flat, cb, cr, dc_l_codes, dc_l_lens, ac_l_codes, ac_l_lens,
              dc_c_codes, dc_c_lens, ac_c_codes, ac_c_lens):
    """Pass 2: gather codes and pack the interleaved 4:2:0 scan.

    y_flat: (nmcu*4, 64); cb, cr: (nmcu, 64).  Table arrays are uint32
    codes / int32 lengths indexed by symbol.
    Returns (packed_bytes, total_bits) — still on device.
    """
    nmcu = cb.shape[0]
    vy, ly = component_entries(component_symbols(y_flat),
                               dc_l_codes, dc_l_lens, ac_l_codes, ac_l_lens)
    vb, lb = component_entries(component_symbols(cb),
                               dc_c_codes, dc_c_lens, ac_c_codes, ac_c_lens)
    vr, lr = component_entries(component_symbols(cr),
                               dc_c_codes, dc_c_lens, ac_c_codes, ac_c_lens)
    e = ENTRIES_PER_BLOCK
    # MCU interleave: Y00 Y01 Y10 Y11 Cb Cr
    vals = jnp.concatenate(
        [vy.reshape(nmcu, 4 * e), vb.reshape(nmcu, e), vr.reshape(nmcu, e)],
        axis=1).reshape(-1)
    lens = jnp.concatenate(
        [ly.reshape(nmcu, 4 * e), lb.reshape(nmcu, e), lr.reshape(nmcu, e)],
        axis=1).reshape(-1)
    return pack_bits(vals, lens)
