"""Zigzag scan orders (JPEG 8x8, H.264 4x4) as gather index tables.

Scans are precomputed numpy index vectors; applying one on TPU is a single
gather over the trailing flattened block dim, fused by XLA.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def _zigzag_order(n: int) -> np.ndarray:
    """Return flat indices of an n*n block in zigzag order."""
    # Even anti-diagonals run up-right, odd run down-left: the standard order
    # starts (0,0),(0,1),(1,0),(2,0),(1,1),(0,2)...
    order = []
    for s in range(2 * n - 1):
        diag = [(i, s - i) for i in range(max(0, s - n + 1), min(s, n - 1) + 1)]
        if s % 2 == 0:
            diag = diag[::-1]  # up-right direction: row decreasing
        order.extend(diag)
    return np.array([i * n + j for i, j in order], dtype=np.int32)


ZIGZAG8 = _zigzag_order(8)          # JPEG 8x8 scan (64 entries)
ZIGZAG4 = _zigzag_order(4)          # H.264 4x4 zigzag scan (16 entries)

_INV8 = np.argsort(ZIGZAG8).astype(np.int32)
_INV4 = np.argsort(ZIGZAG4).astype(np.int32)


def zigzag(blocks, n: int = 8):
    """(..., n, n) -> (..., n*n) in zigzag order."""
    order = ZIGZAG8 if n == 8 else ZIGZAG4
    b = jnp.asarray(blocks)
    flat = b.reshape(b.shape[:-2] + (n * n,))
    return flat[..., jnp.asarray(order)]


def unzigzag(scanned, n: int = 8):
    """(..., n*n) zigzag order -> (..., n, n)."""
    inv = _INV8 if n == 8 else _INV4
    s = jnp.asarray(scanned)
    flat = s[..., jnp.asarray(inv)]
    return flat.reshape(s.shape[:-1] + (n, n))
