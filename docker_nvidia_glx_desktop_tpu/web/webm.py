"""Minimal fragmented WebM (Matroska/EBML) muxer for VP8 over MSE.

The H.264 path ships fMP4 (``web/mp4.py``); VP8 has no MP4 story in
browsers, so the MSE fallback for ``WEBRTC_ENCODER=vp8enc`` uses the
WebM byte-stream format: an init segment (EBML header + Segment start +
Info + Tracks) followed by one Cluster per frame (timestamp +
SimpleBlock), which MediaSource accepts for ``video/webm;
codecs="vp8"``.  Only what MSE requires is emitted, mirroring mp4.py.
"""

from __future__ import annotations

import struct

__all__ = ["WebmMuxer"]


def _id(eid: int) -> bytes:
    out = bytearray()
    while eid:
        out.insert(0, eid & 0xFF)
        eid >>= 8
    return bytes(out)


def _size(n: int) -> bytes:
    """EBML variable-size integer (1-8 bytes)."""
    for length in range(1, 9):
        if n < (1 << (7 * length)) - 1:
            v = n | (1 << (7 * length))
            return v.to_bytes(length, "big")
    raise ValueError("size too large")


UNKNOWN_SIZE = b"\x01\xff\xff\xff\xff\xff\xff\xff"


def _elem(eid: int, payload: bytes) -> bytes:
    return _id(eid) + _size(len(payload)) + payload


def _uint(eid: int, value: int) -> bytes:
    payload = value.to_bytes(max(1, (value.bit_length() + 7) // 8), "big")
    return _elem(eid, payload)


def _float(eid: int, value: float) -> bytes:
    return _elem(eid, struct.pack(">d", value))


def _string(eid: int, s: str) -> bytes:
    return _elem(eid, s.encode())


class WebmMuxer:
    """``init_segment()`` once, then ``fragment(frame, keyframe, pts_ms)``
    per VP8 frame."""

    TIMESCALE_NS = 1_000_000          # 1 ms ticks

    def __init__(self, width: int, height: int, fps: float = 30.0):
        self.width, self.height = width, height
        self.fps = fps
        self._frame = 0

    @property
    def mime(self) -> str:
        return 'video/webm; codecs="vp8"'

    def init_segment(self) -> bytes:
        ebml = _elem(0x1A45DFA3, b"".join([
            _uint(0x4286, 1),             # EBMLVersion
            _uint(0x42F7, 1),             # EBMLReadVersion
            _uint(0x42F2, 4),             # EBMLMaxIDLength
            _uint(0x42F3, 8),             # EBMLMaxSizeLength
            _string(0x4282, "webm"),      # DocType
            _uint(0x4287, 2),             # DocTypeVersion
            _uint(0x4285, 2),             # DocTypeReadVersion
        ]))
        info = _elem(0x1549A966, b"".join([
            _uint(0x2AD7B1, self.TIMESCALE_NS),      # TimestampScale
            _string(0x4D80, "tpu-desktop"),          # MuxingApp
            _string(0x5741, "tpu-desktop"),          # WritingApp
        ]))
        video = _elem(0xE0, b"".join([
            _uint(0xB0, self.width),                 # PixelWidth
            _uint(0xBA, self.height),                # PixelHeight
        ]))
        track = _elem(0xAE, b"".join([
            _uint(0xD7, 1),                          # TrackNumber
            _uint(0x73C5, 1),                        # TrackUID
            _uint(0x83, 1),                          # TrackType: video
            _uint(0x9C, 0),                          # FlagLacing
            _string(0x86, "V_VP8"),                  # CodecID
            video,
        ]))
        tracks = _elem(0x1654AE6B, track)
        segment_start = _id(0x18538067) + UNKNOWN_SIZE   # streaming
        return ebml + segment_start + info + tracks

    def fragment(self, frame: bytes, keyframe: bool = True,
                 pts_ms: int = None) -> bytes:
        """One Cluster per frame (lowest-latency MSE granularity).

        ``pts_ms``: real capture timestamp; without it the timeline is
        synthesized from the nominal fps, which drifts from wall-clock
        whenever damage gating makes the frame cadence irregular."""
        if pts_ms is None:
            pts_ms = int(self._frame * 1000 / max(self.fps, 1))
        self._frame += 1
        # SimpleBlock: track vint(0x81) + s16 rel. timestamp + flags
        flags = 0x80 if keyframe else 0x00
        sb = _elem(0xA3, b"\x81\x00\x00" + bytes([flags]) + frame)
        return _elem(0x1F43B675, _uint(0xE7, pts_ms) + sb)
