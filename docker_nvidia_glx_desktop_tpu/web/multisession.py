"""Multi-session batch serving: N desktops, one batched TPU encode.

The reference's concurrency model is one container per session per GPU
(reference README.md:24,180-182).  The rebuild's TPU-native answer
(SURVEY.md §2.3, BASELINE config 5) is batch encoding: N sessions' frames
stacked on the leading axis and encoded by ONE `shard_map`ped device
program over a ("session", "spatial") mesh — one host serves N desktops,
and a pod slice scales the batch.

``BatchStreamManager`` runs the single encode loop; each
:class:`SessionHub` carries one session's muxer/subscribers/stats and
plugs into the same websocket handler a single :class:`StreamSession`
does (``server.py`` routes ``/ws?session=i``).

GOP mode is batched too: non-key ticks run the context-parallel P step
(``parallel.batch.h264_p_batch_step`` — ME/MC with inter-shard halo
exchange; sharded AUs byte-identical to the single-device GOP encode,
``tests/test_parallel.py::test_context_parallel_p_byte_identical``) with
the reference planes held sharded on device.  All sessions in a bucket
share one GOP phase: the batch is ONE compiled device program per tick,
so a forced IDR (join, eviction recovery, shard overflow) re-keys every
session in the bucket — the per-hub request_idr rate window bounds how often
one client can impose that cost on its bucket-mates.  Geometry whose
spatial shards cannot donate the P halo serves all-intra
(``p_halo_feasible``).
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import List, Optional

import numpy as np

from ..models.h264 import H264Encoder
from ..obs import events as obsev
from ..obs import journey as obsj
from ..obs import metrics as obsm
from ..obs.trace import next_frame_id, tracer
from ..resilience import faults as rfaults
from ..utils.config import Config
from ..utils.timing import FrameStats
from .mp4 import Mp4Muxer, split_annexb
from .session import M_IDR_REQUESTS, SubscriberSet

log = logging.getLogger(__name__)

__all__ = ["SessionHub", "BatchStreamManager"]

# Batched-path analogs of the single-session encoder histograms: submit
# = host YUV staging + async device dispatch of the whole batch, collect
# = device wait + host transfer of every session's shards.
_M_BATCH_SUBMIT = obsm.histogram(
    "dngd_batch_submit_ms",
    "Batched step device dispatch time per tick (all sessions)")
_M_BATCH_COLLECT = obsm.histogram(
    "dngd_batch_collect_ms",
    "Batched step device wait + host transfer per tick (all sessions)")
_M_BATCH_TICKS = obsm.counter(
    "dngd_batch_ticks_total", "Batched encode ticks delivered", ("kind",))
_M_MESH_REBUILDS = obsm.counter(
    "dngd_mesh_rebuilds_total",
    "Elastic mesh rebuilds after chip loss (N->N-1 re-bucketing)")
_M_MESH_CHIPS = obsm.gauge(
    "dngd_mesh_dead_chips", "Mesh chips currently marked dead")


class SessionHub:
    """One session's client-facing state (no encode thread of its own).

    ``injector`` is per-hub: only the hub whose source is a real X display
    gets a real input backend — otherwise a client on session 1 would
    inject keystrokes into session 0's desktop."""

    def __init__(self, cfg: Config, source, sps: bytes, pps: bytes,
                 codec_name: str, injector=None):
        self.cfg = cfg
        self.source = source
        self.codec_name = codec_name
        self.injector = injector
        self.stats = FrameStats()
        self.muxer = Mp4Muxer(source.width, source.height, sps, pps,
                              fps=cfg.refresh)
        self.init_segment = self.muxer.init_segment()
        self._subscribers = SubscriberSet()
        # per-hub glass-to-glass journeys (obs/journey): minted by the
        # manager at delivery, closed by the hub's clients' ws acks
        self.journeys = obsj.JourneyBook()
        # request_idr rate limiter (loop-only state: every caller —
        # PLI dispatch, ws handler, degrade executor — runs on the
        # event loop, unlike StreamSession's locked twin)
        self._idr_last_grant = -1e9
        self._idr_deferred = False

    @property
    def mime(self) -> str:
        return self.muxer.mime

    def hello(self) -> dict:
        return {"type": "hello", "codec": self.codec_name,
                "mime": self.mime, "width": self.source.width,
                "height": self.source.height}

    # the websocket handler's session protocol -------------------------

    on_keyframe_request = None     # set by the manager (GOP resync)

    def subscribe(self, maxsize: int = 8) -> asyncio.Queue:
        q = self._subscribers.subscribe(
            [("init", self.init_segment)], maxsize=maxsize, want_key=True)
        self.request_keyframe()    # joiners mid-GOP need an IDR to start
        return q

    def unsubscribe(self, q: asyncio.Queue) -> None:
        self._subscribers.unsubscribe(q)

    def close(self) -> None:
        """Drop every subscriber and deregister from the scrape-time
        client/queue-depth gauges (see StreamSession.close)."""
        self._subscribers.close()
        self.journeys.close_book()

    def rebucket(self, sps: bytes, pps: bytes) -> list:
        """Adopt a re-bucketed geometry (elastic failover resolution
        downshift): rebuild the muxer for the source's NEW size and
        return the hello + init items to re-announce so MSE clients
        re-init without renegotiating the websocket.  Runs on the
        encode thread (the swap must land before the next tick's
        fragment); the caller marshals the broadcast to the loop."""
        self.muxer = Mp4Muxer(self.source.width, self.source.height,
                              sps, pps, fps=self.cfg.refresh)
        self.init_segment = self.muxer.init_segment()
        return [("json", self.hello()), ("init", self.init_segment)]

    @property
    def encoder(self):
        return self            # request_keyframe target

    def request_keyframe(self) -> None:
        if self.on_keyframe_request is not None:
            self.on_keyframe_request()   # GOP mode: force the next IDR

    # One forced IDR per window (the StreamSession.request_idr
    # contract): in GOP mode request_keyframe fans out through the
    # manager to EVERY co-tenant session's next frame, so an unlimited
    # PLI storm here has the largest blast radius in the system.
    IDR_MIN_INTERVAL_S = 1.0

    def request_idr(self, reason: str = "manual") -> bool:
        """Rate-limited, deduped forced-IDR (PLI/FIR, degrade rung).
        The hub has no encode loop of its own, so an over-limit
        request defers via ``loop.call_later`` instead of a tick."""
        M_IDR_REQUESTS.labels(reason).inc()
        now = time.monotonic()
        if now - self._idr_last_grant >= self.IDR_MIN_INTERVAL_S:
            self._idr_last_grant = now
            self._idr_deferred = False
            self.request_keyframe()
            return True
        if not self._idr_deferred:
            self._idr_deferred = True
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                pass                     # no loop: collapse silently —
            else:                        # the next grantable call wins
                loop.call_later(
                    self.IDR_MIN_INTERVAL_S
                    - (now - self._idr_last_grant),
                    self._grant_deferred_idr)
        return False

    def _grant_deferred_idr(self) -> None:
        if not self._idr_deferred:
            return
        self._idr_deferred = False
        self._idr_last_grant = time.monotonic()
        self.request_keyframe()

    def stats_summary(self) -> dict:
        s = self.stats.summary()
        s.update({"codec": self.codec_name, "width": self.source.width,
                  "height": self.source.height,
                  "clients": len(self._subscribers)})
        return s

    def publish(self, fragment: bytes, keyframe: bool = True,
                fid: int = 0) -> None:
        if self._subscribers.publish(("frag", fragment, keyframe, fid),
                                     keyframe=keyframe):
            # a slow client lost its keyframe; request_idr's shared
            # rate window keeps one stalled client from storming every
            # co-tenant session's GOP
            self.request_idr("evict")


class BatchStreamManager:
    """One encode loop batch-encoding every session's frames on the mesh."""

    def __init__(self, cfg: Config, sources: List, loop=None,
                 injectors: Optional[List] = None):
        from ..parallel import batch

        self.cfg = cfg
        self.loop = loop
        self.sources = sources
        w, h = sources[0].width, sources[0].height
        # One compiled step serves one PADDED geometry; sessions may differ
        # in raw size within the same MB-padded bucket (each hub's own SPS
        # carries its crop window).  Mixed padded geometries are composed
        # by BucketedStreamManager.
        probe = H264Encoder(w, h, qp=cfg.encoder_qp, mode="cavlc")
        self._probe = probe
        probes = [probe if (s.width, s.height) == (w, h)
                  else H264Encoder(s.width, s.height, qp=cfg.encoder_qp,
                                   mode="cavlc")
                  for s in sources]
        assert all((p.pad_h, p.pad_w) == (probe.pad_h, probe.pad_w)
                   for p in probes), \
            "batched sessions share one padded geometry (see " \
            "BucketedStreamManager for mixed buckets)"
        if cfg.codec != "tpuh264enc":
            # The batched device program is the intra CAVLC pipeline; other
            # codec selections fall back to it rather than silently or
            # loudly failing N sessions.
            log.warning("WEBRTC_ENCODER=%s is not batchable; multi-session "
                        "mode serves h264_cavlc", cfg.webrtc_encoder)

        injectors = injectors or [None] * len(sources)
        self.hubs = []
        self._hub_headers = []
        for src, inj, pr in zip(sources, injectors, probes):
            nals = split_annexb(pr.headers())
            sps = next(n for n in nals if (n[0] & 0x1F) == 7)
            pps = next(n for n in nals if (n[0] & 0x1F) == 8)
            self.hubs.append(SessionHub(cfg, src, sps, pps, "h264_cavlc",
                                        injector=inj))
            self._hub_headers.append(pr.headers())
        self._hub_probes = probes

        import jax

        shape = cfg.mesh_shape
        ndev = len(jax.devices())
        total = int(np.prod(shape))
        if total > ndev or len(shape) > 2:
            log.warning("TPU_MESH %s needs %d devices, have %d; using 1",
                        shape, total, ndev)
            shape = (1, 1)
        if len(shape) == 1:
            shape = (shape[0], 1)
        if len(sources) % shape[0] != 0:
            # shard_map needs the session batch divisible by the session
            # axis; shrink the axis to the largest divisor that fits.
            ns = shape[0]
            while ns > 1 and len(sources) % ns != 0:
                ns -= 1
            log.warning("%d sessions not divisible over %d-way session "
                        "axis; using %d", len(sources), shape[0], ns)
            shape = (ns, shape[1])
        nx = shape[1]
        if probe.pad_h % (16 * nx) != 0:
            log.warning("height %d cannot split over %d spatial shards; "
                        "using 1", probe.pad_h, nx)
            shape = (shape[0], 1)
        # spatial planning (ENCODER_SPATIAL_SHARDS): when the knob asks
        # for — or "auto" models — more than one chip per session and
        # TPU_MESH did not already pin a spatial extent, replan_mesh
        # trades the session axis for spatial shards: eight 1080p
        # sessions stay one-per-chip on the session axis, one 4K
        # session spreads its MB rows across the chips its modeled
        # per-chip cost demands (fleet/capacity.chips_for_session)
        shape = self._plan_spatial_extent(cfg, probe, shape, ndev)
        # elastic failover state: the full device pool minus chips marked
        # dead; a mesh_chip_lost event re-plans onto the survivors
        self._all_devices = list(jax.devices())
        self._dead_devices: list = []
        self._native_geom = (w, h)
        self._rebuilds = 0
        self.mesh = batch.make_mesh(shape, self._all_devices[:shape[0] * shape[1]])
        # GOP over the mesh needs the context-parallel P step (reference
        # halo exchange); geometry that can't donate the halo serves
        # all-intra instead.
        self.gop = max(int(cfg.encoder_gop), 1)
        if self.gop > 1 and not batch.p_halo_feasible(probe.pad_h, shape[1]):
            log.warning("spatial shards too short for the P-frame halo; "
                        "multi-session mode serves all-intra")
            self.gop = 1
        self.step, self.rows_local = batch.h264_batch_encode_step(
            self.mesh, probe.pad_h, probe.pad_w, qp=cfg.encoder_qp,
            with_recon=self.gop > 1)
        self.p_step = None
        # GOP-chunk super-step (ENCODER_SUPERSTEP_CHUNK): P ticks stage
        # host-side and a full chunk dispatches as ONE shard_map program
        # with the reference ring donated in place (parallel/batch.
        # h264_p_chunk_batch_step); 0 = per-tick dispatch
        self.chunk = (max(2, min(int(getattr(cfg, "encoder_chunk", 0)), 6))
                      if getattr(cfg, "encoder_chunk", 0) >= 2
                      and self.gop > 1 else 0)
        self.chunk_step = None
        self._stage: list = []           # staged (ys, cbs, crs, frame_num)
        self._stage_hdr_cache = {}
        if self.gop > 1:
            self.p_step, _ = batch.h264_p_batch_step(
                self.mesh, probe.pad_h, probe.pad_w, qp=cfg.encoder_qp)
            if self.chunk:
                self.chunk_step, _ = batch.h264_p_chunk_batch_step(
                    self.mesh, probe.pad_h, probe.pad_w, self.chunk,
                    qp=cfg.encoder_qp)
        self.headers = probe.headers()
        self._batch = batch
        self._refs = None                    # sharded device planes
        self._gop_pos = 0
        self._frame_num = 0
        self._idr_count = 0
        self._force_idr = False
        self._p_hdr_cache = {}
        self._tracer = tracer("batch")
        self._m_idr_ticks = _M_BATCH_TICKS.labels("idr")
        self._m_p_ticks = _M_BATCH_TICKS.labels("p")
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._last_tick = time.monotonic()   # loop liveness (healthz)
        self._last_seqs = [-1] * len(sources)
        # first batched step jit-compiles; don't let the liveness probe
        # read that as a stall (see StreamSession.COMPILE_GRACE_S)
        self._healthz_grace_until = time.monotonic() + 180.0
        # consecutive organic tick failures escalate to chip-lost
        # re-bucketing (same machinery as the mesh_chip_lost injection)
        from ..resilience.policy import CircuitBreaker
        self._tick_breaker = CircuitBreaker(failure_threshold=5,
                                            reset_timeout_s=5.0)
        # fleet-wide degrade ladder (fleet/scheduler backpressure hook):
        # the event loop queues a level, the encode thread applies it
        # between ticks (muxer swaps must land there)
        self._pending_degrade: Optional[int] = None
        self._degrade_level = 0
        # wired unconditionally: in all-intra mode the forced-IDR flag
        # still WAKES the damage-gated loop so a joiner on a static
        # desktop gets its first (intra) frame
        for hub in self.hubs:
            hub.on_keyframe_request = self.request_keyframe_all
        # declare the serving context so the ledger's measured costs are
        # attributable to a geometry x session count — what the fleet
        # capacity model (fleet/capacity) divides by.  Multi-bucket
        # deployments overwrite each other here (one global ledger);
        # last bucket wins, which is the conservative larger-geometry
        # one under the bucket ordering.
        self._set_ledger_context()
        # flight-recorder postmortems embed the mesh picture (same
        # last-bucket-wins convention as the ledger context above)
        from ..obs import flight as obsf
        obsf.register_state_provider("mesh", self.stats_summary)

    def _plan_spatial_extent(self, cfg, probe, shape, ndev):
        """Resolve the mesh's spatial extent from ENCODER_SPATIAL_SHARDS
        ("auto" = the capacity model's chips-per-session for this
        bucket's geometry at the configured refresh).  Only engages when
        the operator's TPU_MESH left the spatial axis at 1 — an explicit
        mesh shape always wins."""
        from ..parallel import batch

        knob = str(getattr(cfg, "encoder_spatial_shards", "0") or "0")
        knob = knob.strip()
        if shape[1] != 1 or knob in ("0", "1", "off", ""):
            return shape
        if knob == "auto":
            from ..models.h264 import spatial_auto_shards
            want = spatial_auto_shards(probe.width, probe.height,
                                       float(self.cfg.refresh),
                                       n_devices=ndev)
        else:
            try:
                want = int(knob)
            except ValueError:
                log.warning("ENCODER_SPATIAL_SHARDS=%r not understood; "
                            "spatial sharding off", knob)
                return shape
        if want <= 1 or ndev <= 1:
            return shape
        want = batch.feasible_spatial_shards(probe.pad_h, want, ndev)
        ns, nx = batch.replan_mesh(len(self.sources), ndev,
                                   probe.pad_h, want_nx=want)
        if nx <= 1:
            return shape
        log.warning("spatial mesh plan: %d session(s) on a (%d session "
                    "x %d spatial) mesh (%s shard count)",
                    len(self.sources), ns, nx,
                    "modeled" if knob == "auto" else "pinned")
        return (ns, nx)

    def _set_ledger_context(self) -> None:
        from ..obs.budget import LEDGER
        LEDGER.set_context(self._probe.width, self._probe.height,
                           self.cfg.refresh, sessions=len(self.sources))

    def session(self, idx: int):
        return self.hubs[idx] if 0 <= idx < len(self.hubs) else None

    def stats_summary(self) -> dict:
        return {"sessions": [h.stats_summary() for h in self.hubs],
                "mesh": list(self.mesh.devices.shape),
                "dead_chips": len(self._dead_devices),
                "mesh_rebuilds": self._rebuilds,
                "degrade_level": self._degrade_level,
                "geometry": f"{self._probe.width}x{self._probe.height}"}

    def surviving_chips(self) -> int:
        """Live chip count (the fleet scheduler's capacity input)."""
        return len(self._surviving())

    def applied_degrade_level(self) -> int:
        """The degrade rung ACTUALLY serving (the fleet scheduler's
        capacity-model input — a refused re-bucket must not let modeled
        capacity rise on a geometry shrink that never happened)."""
        return self._degrade_level

    # -- encode loop ---------------------------------------------------

    def start(self) -> None:
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="batch-encode")
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=15)
            self._thread = None

    def close(self) -> None:
        """Stop the encode loop and release every hub's observability
        state (scrape-time gauges over subscriber sets)."""
        self.stop()
        for hub in self.hubs:
            hub.close()
        from ..obs.budget import LEDGER
        LEDGER.clear_context()

    def _planes(self, rgb, i: int = 0):
        probe = self._hub_probes[i]
        planes = probe._host_yuv420(rgb)
        if planes is not None:
            return planes
        from ..models.h264 import _yuv_stage
        y, cb, cr = _yuv_stage(rgb, probe.pad_h, probe.pad_w)
        return np.asarray(y), np.asarray(cb), np.asarray(cr)

    def _run(self) -> None:
        frame_interval = 1.0 / max(self.cfg.refresh, 1)
        while not self._stop.is_set():
            spec = rfaults.fire("mesh_chip_lost")
            if spec is not None:
                self.mark_chip_dead(int(spec.get("chip", -1)))
            pend = self._pending_degrade
            if pend is not None:
                self._pending_degrade = None
                self._apply_degrade_level(pend)
            t0 = time.perf_counter()
            frames = []
            # a pending forced IDR (new joiner) overrides the damage gate:
            # static desktops must still produce the un-gating keyframe
            changed = self._force_idr
            for i, src in enumerate(self.sources):
                rgb, seq = src.frame()
                changed |= seq != self._last_seqs[i]
                self._last_seqs[i] = seq
                frames.append(rgb)
            has_clients = any(h._subscribers for h in self.hubs)
            if not changed:
                # legitimate idleness = liveness progress (healthz);
                # staged super-step frames must not strand — flush the
                # partial chunk through the per-tick step first
                if self._stage:
                    try:
                        for flat, idr, jmeta in self._chunk_flush():
                            self._deliver_tick(
                                flat, idr,
                                (time.perf_counter() - t0) * 1e3,
                                jmeta)
                    except Exception:
                        log.exception("partial-chunk flush failed; "
                                      "forcing IDR resync")
                        self._stage.clear()
                        self._force_idr = True
                self._last_tick = time.monotonic()
                time.sleep(frame_interval / 4 if has_clients
                           else min(frame_interval * 4, 0.25))
                continue
            planes = [self._planes(f, i) for i, f in enumerate(frames)]
            ys = np.stack([p[0] for p in planes])
            cbs = np.stack([p[1] for p in planes])
            crs = np.stack([p[2] for p in planes])
            try:
                results = self._encode_tick(ys, cbs, crs)
            except Exception:
                # consecutive tick failures = a chip is actually gone
                # (organic analog of the mesh_chip_lost injection):
                # re-bucket onto the survivors instead of spinning
                self._tick_breaker.record_failure()
                self._stage.clear()          # staged frames died too
                self._force_idr = True
                if (self._tick_breaker.state == "open"
                        and len(self._surviving()) > 1):
                    # probe each survivor so the EVICTED chip is the one
                    # that actually stopped answering — blindly dropping
                    # the last chip would shed healthy capacity while
                    # the dead one keeps poisoning every tick
                    victim = self._probe_dead_chip()
                    log.exception("batch encode failed %d times; marking "
                                  "chip %s dead and re-bucketing",
                                  self._tick_breaker.consecutive_failures,
                                  victim)
                    self.mark_chip_dead(victim)
                    self._tick_breaker.record_success()
                else:
                    log.exception("batch encode failed; dropping tick")
                time.sleep(frame_interval)
                continue
            self._tick_breaker.record_success()
            t_enc = (time.perf_counter() - t0) * 1e3
            delivered = False
            for flat, idr, jmeta in results:
                delivered |= self._deliver_tick(flat, idr, t_enc, jmeta)
            if delivered:
                self._last_tick = time.monotonic()   # progress (healthz)
            elapsed = time.perf_counter() - t0
            sleep = frame_interval - elapsed
            if sleep > 0:
                time.sleep(sleep if has_clients
                           else min(sleep * 4, 0.25))

    def _deliver_tick(self, flat, idr: bool, t_enc: float,
                      jmeta: Optional[dict] = None) -> bool:
        """Assemble + publish one tick's AUs for every hub; returns
        whether anything was delivered (healthz progress).  ``jmeta``
        carries the super-step chunk identity so every hub's journey
        amortizes the chunk's one dispatch honestly."""
        from ..bitstream import h264 as syn

        t_now = time.perf_counter()
        shards = int(self.mesh.devices.shape[1])
        delivered = False
        for i, hub in enumerate(self.hubs):
            try:
                au = self._batch.assemble_session_h264(
                    flat[i], self.rows_local,
                    headers=self._hub_headers[i] if idr else b"",
                    nal_type=None if idr else syn.NAL_SLICE,
                    ref_idc=3 if idr else 2)
            except AssertionError:
                log.warning("session %d: shard overflow; frame dropped",
                            i)
                self._force_idr = True   # resync the GOP next tick
                continue
            frag = hub.muxer.fragment(au, keyframe=idr)
            hub.stats.record_frame(t_enc, len(frag))
            # per-hub journey: capture approximated by tick start (the
            # batch path has no per-hub capture stamp), chunk identity
            # shared across the whole batch tick
            fid = next_frame_id()
            hub.journeys.mint(fid, t_capture=t_now - t_enc / 1e3)
            meta = dict(jmeta) if jmeta else {}
            meta.setdefault("shards", shards)
            # the chunk's slot-0 frame carries the whole chunk's device
            # cost (mirrors the super-step ring: staged frames cost ~0);
            # amortization spreads it back over the chunk at export
            dev = (t_enc if not meta.get("chunk_id")
                   or meta.get("slot", 0) == 0 else 0.0)
            hub.journeys.complete(fid, t_now, device_ms=dev, meta=meta)
            self._post(hub, frag, idr, fid)
            delivered = True
        return delivered

    def _encode_tick(self, ys, cbs, crs):
        """One capture tick -> list of (flat_shards, is_idr) AU batches,
        advancing the GOP state machine (intra-only when gop == 1).

        Per-tick mode returns exactly one entry.  Super-step mode
        (``self.chunk``) STAGES P ticks host-side and returns [] until
        the chunk fills, then dispatches the whole chunk as one device
        program and returns its ``chunk`` frames at once; an IDR due
        with a partial stage flushes the stage through the per-tick
        step first (byte-identical path)."""
        t0 = time.perf_counter()
        idr = (self.gop == 1 or self._gop_pos == 0 or self._force_idr
               or self._refs is None)
        if not idr and self.chunk_step is not None:
            return self._chunk_stage_tick(ys, cbs, crs, t0)
        out = []
        if self._stage:
            # IDR due with a partial chunk staged: flush it per-tick so
            # the ring never straddles the reference-chain reset
            out.extend(self._chunk_flush())
        fid = next_frame_id()
        if idr:
            self._force_idr = False
            self._gop_pos = 0
            self._frame_num = 0
            # Consecutive IDR AUs must carry different idr_pic_id
            # (H.264 7.4.3) — alternate parity like the single-session
            # encoder's _idr_count % 2.
            step_out = self.step(ys, cbs, crs,
                                 idr_parity=self._idr_count & 1)
            self._idr_count += 1
            if self.gop > 1:
                flat, ry, rcb, rcr = step_out
                self._refs = (ry, rcb, rcr)
            else:
                flat = step_out
        else:
            self._frame_num = (self._frame_num + 1) % 16
            hv, hl = self._p_hdr(self._frame_num)
            flat, ry, rcb, rcr = self.p_step(
                ys, cbs, crs, *self._refs, hv, hl)
            self._refs = (ry, rcb, rcr)
        if self.gop > 1:
            self._gop_pos = (self._gop_pos + 1) % self.gop
        # dispatch is async; np.asarray is the device wait + transfer
        t_sub = time.perf_counter()
        flat_np = np.asarray(flat)
        t_col = time.perf_counter()
        _M_BATCH_SUBMIT.observe((t_sub - t0) * 1e3)
        _M_BATCH_COLLECT.observe((t_col - t_sub) * 1e3)
        (self._m_idr_ticks if idr else self._m_p_ticks).inc()
        self._tracer.record_marks(fid, (
            ("device-submit", t0), ("device-dispatch", t_sub),
            ("device-collect", t_col)), meta=(("session", "batch"),))
        out.append((flat_np, idr, None))
        return out

    # -- GOP-chunk super-step staging (parallel/batch chunk step) ------

    def _chunk_stage_tick(self, ys, cbs, crs, t0: float):
        self._frame_num = (self._frame_num + 1) % 16
        self._gop_pos = (self._gop_pos + 1) % self.gop
        self._stage.append((ys, cbs, crs, self._frame_num))
        if len(self._stage) < self.chunk:
            return []
        stage, self._stage = self._stage, []
        fid = next_frame_id()
        ys_c = np.stack([s[0] for s in stage], axis=1)
        cbs_c = np.stack([s[1] for s in stage], axis=1)
        crs_c = np.stack([s[2] for s in stage], axis=1)
        hv, hl = self._chunk_hdrs(tuple(s[3] for s in stage))
        # the sharded reference ring is DONATED to the chunk program
        # and returned under the same sharding spec — aliased in place,
        # never repartitioned (parallel/batch.h264_p_chunk_batch_step)
        flats, ry, rcb, rcr = self.chunk_step(
            ys_c, cbs_c, crs_c, *self._refs, hv, hl)
        self._refs = (ry, rcb, rcr)
        t_sub = time.perf_counter()
        flat_np = np.asarray(flats)            # (S, K, nx, L)
        t_col = time.perf_counter()
        _M_BATCH_SUBMIT.observe((t_sub - t0) * 1e3)
        _M_BATCH_COLLECT.observe((t_col - t_sub) * 1e3)
        self._m_p_ticks.inc(len(stage))
        # chunk=/chunk_len= args name this super-step lane in the
        # Chrome export — a chunk tick is one span covering K frames
        self._tracer.record_marks(fid, (
            ("device-submit", t0), ("device-dispatch", t_sub),
            ("device-collect", t_col)),
            meta=(("session", "batch"), ("chunk", fid),
                  ("chunk_len", len(stage))))
        return [(flat_np[:, k], False,
                 {"chunk_id": fid, "slot": k, "chunk_len": len(stage)})
                for k in range(len(stage))]

    def _chunk_flush(self):
        """Push a PARTIAL chunk through the per-tick P step (IDR due or
        idle drain) — byte-identical to the chunk path, so this is a
        pure latency/dispatch decision."""
        stage, self._stage = self._stage, []
        out = []
        for ys, cbs, crs, fn in stage:
            hv, hl = self._p_hdr(fn)
            flat, ry, rcb, rcr = self.p_step(
                ys, cbs, crs, *self._refs, hv, hl)
            self._refs = (ry, rcb, rcr)
            self._m_p_ticks.inc()
            # flushed frames went per-tick: unchunked journey identity
            # (the chunk-flush boundary must not fake an amortized span)
            out.append((np.asarray(flat), False, None))
        return out

    def _chunk_hdrs(self, fns: tuple):
        """K frames' slice-header slots stacked on the scan axis
        (cached per frame_num sequence — bounded by the mod-16 cycle)."""
        got = self._stage_hdr_cache.get(fns)
        if got is None:
            hvs, hls = zip(*(self._p_hdr(fn) for fn in fns))
            got = (np.stack(hvs), np.stack(hls))
            self._stage_hdr_cache[fns] = got
        return got

    def _p_hdr(self, frame_num: int):
        slots = self._p_hdr_cache.get(frame_num)
        if slots is None:
            from ..ops import cavlc_device
            hv, hl = cavlc_device.slice_header_slots(
                self._probe.mb_h, self._probe.mb_w, frame_num=frame_num,
                slice_type=5, idr=False)
            slots = (np.asarray(hv), np.asarray(hl))
            self._p_hdr_cache[frame_num] = slots
        return slots

    def request_keyframe_all(self) -> None:
        self._force_idr = True

    # -- fleet-wide degrade (fleet/scheduler backpressure hook) --------

    def request_degrade_level(self, level: int) -> None:
        """Queue a degrade-ladder level (0 = native) for EVERY session
        in the bucket: the MB-snapped resolution downshift grows the
        modeled sessions-per-chip so admission capacity rises before
        anyone is shed.  Applied by the encode thread between ticks."""
        self._pending_degrade = int(level)

    def _apply_degrade_level(self, level: int) -> None:
        """Encode-thread half of :meth:`request_degrade_level`: rebuild
        the bucket at the requested rung (same machinery as the elastic
        chip-loss re-bucket — geometry, steps, recovery IDR, client
        re-announce), tracked so restores are idempotent.  The level is
        FLOORED at the elastic chip-loss recommendation: a backpressure
        RESTORE must never rebuild at a geometry a shrunken mesh cannot
        sustain (the mirror of the floor inside _rebuild_mesh)."""
        batch = self._batch
        level = max(int(level), batch.elastic_degrade_level(
            len(self.sources), len(self._surviving())))
        level = max(0, min(level, len(batch.DEGRADE_SCALES) - 1))
        if level == self._degrade_level:
            return
        if self._rebucket_target(level) is None:
            # refusal known up front (resize off, non-uniform sources,
            # or already serving that geometry): the rebuild would cost
            # a recompile + fleet-wide recovery IDR for zero capacity.
            # When the mesh already serves the rung's geometry, claim
            # the level so stats stay honest and the no-op guard holds.
            nw, nh = self._native_geom
            if batch.degraded_geometry(nw, nh, level) == (
                    self._probe.width, self._probe.height):
                self._degrade_level = level
            return
        log.warning("fleet degrade: re-bucketing all %d sessions to "
                    "ladder level %d", len(self.sources), level)
        # _rebuild_mesh records _degrade_level itself — and only when
        # the re-bucket genuinely applied
        self._rebuild_mesh(self._surviving(), level=level)

    # -- elastic multichip failover (resilience/continuity leg 2) ------

    def _surviving(self) -> list:
        return [d for d in self._all_devices if d not in self._dead_devices]

    def _probe_dead_chip(self) -> int:
        """Index (into the surviving list) of the first chip that fails
        a tiny put/pull round-trip, or -1 when every chip answers (a
        collective failure — evict the last, the least-disruptive
        default for the prefix-assignment rebuild)."""
        import jax

        for i, dev in enumerate(self._surviving()):
            try:
                np.asarray(jax.device_put(np.zeros(1, np.uint8), dev))
            except Exception:
                return i
        return -1

    def mark_chip_dead(self, chip: int = -1) -> None:
        """Declare one mesh chip lost and re-bucket onto the survivors.

        ``chip`` indexes the CURRENT surviving list (-1 = the last chip,
        the default the fault injection uses).  Runs on the encode
        thread between ticks; sessions displaced off the dead chip
        restart from their host-side GOP checkpoint (the counters below
        — ``_gop_pos``/``_frame_num``/``_idr_count`` — ARE that
        checkpoint; only the device-resident reference planes died) via
        the recovery IDR the rebuild forces."""
        surviving = self._surviving()
        if len(surviving) <= 1:
            log.error("mesh chip lost with no spare device; keeping the "
                      "current mesh and hoping for a reset")
            return
        idx = chip if 0 <= chip < len(surviving) else len(surviving) - 1
        dead = surviving.pop(idx)
        self._dead_devices.append(dead)
        _M_MESH_CHIPS.set(len(self._dead_devices))
        log.warning("mesh chip %s lost; re-bucketing %d sessions onto "
                    "%d surviving chips", dead, len(self.sources),
                    len(surviving))
        obsev.emit("chip-loss", point=str(dead),
                   survivors=len(surviving),
                   sessions=len(self.sources))
        self._rebuild_mesh(surviving)

    def _rebuild_mesh(self, surviving: list, level: int = None) -> None:
        """Compile the batch step(s) over an (N-1)-chip mesh.

        The halo-exchange neighbor pairs are derived from the new
        spatial extent inside ``h264_p_batch_step``, so rebuilding the
        step IS the halo rewire.  GOP lineage (idr_pic_id parity,
        frame_num phase) carries over on the host; the reference planes
        are gone with the old mesh, so the next tick is a recovery IDR
        for every session in the bucket.

        ``level`` pins the degrade-ladder rung (the fleet backpressure
        path); None derives it from the chip:session ratio (the elastic
        chip-loss path)."""
        batch = self._batch
        probe = self._probe
        want_nx = self.mesh.devices.shape[1]
        if level is None:
            # chip loss must never UNDO a fleet-backpressure rung: the
            # elastic recommendation floors at the level already engaged
            level = max(batch.elastic_degrade_level(len(self.sources),
                                                    len(surviving)),
                        self._degrade_level)
        if level or self._degrade_level:
            # level 0 through this branch RESTORES native geometry
            # (degraded_geometry(native, 0) == native)
            self._maybe_rebucket_geometry(level)
            probe = self._probe              # may have changed
        ns, nx = batch.replan_mesh(len(self.sources), len(surviving),
                                   probe.pad_h, want_nx=want_nx)
        self.mesh = batch.make_mesh((ns, nx), surviving[:ns * nx])
        self.step, self.rows_local = batch.h264_batch_encode_step(
            self.mesh, probe.pad_h, probe.pad_w, qp=self.cfg.encoder_qp,
            with_recon=self.gop > 1)
        self.p_step = None
        self.chunk_step = None
        if self.gop > 1:
            if batch.p_halo_feasible(probe.pad_h, nx):
                self.p_step, _ = batch.h264_p_batch_step(
                    self.mesh, probe.pad_h, probe.pad_w,
                    qp=self.cfg.encoder_qp)
                if self.chunk:
                    self.chunk_step, _ = batch.h264_p_chunk_batch_step(
                        self.mesh, probe.pad_h, probe.pad_w, self.chunk,
                        qp=self.cfg.encoder_qp)
            else:
                log.warning("re-bucketed spatial shards too short for "
                            "the P halo; bucket serves all-intra now")
                self.gop = 1
        # displaced sessions restart from the checkpoint: counters kept,
        # the reference RING and any staged chunk died with the old mesh
        # -> re-seed: next tick is a recovery IDR whose recon re-seeds
        # the donated ring on the new mesh
        self._refs = None
        self._stage.clear()
        self._force_idr = True
        self._p_hdr_cache.clear()
        self._stage_hdr_cache.clear()
        self._rebuilds += 1
        # track the rung ACTUALLY serving (both the chip-loss and the
        # backpressure path land here): a stale level would misreport
        # stats and let the next request_degrade_level pass the no-op
        # guard into a redundant recompile + IDR burst.  Only claim the
        # rung when the re-bucket really applied (it refuses when
        # resize is off or sources are non-uniform).
        gw, gh = batch.degraded_geometry(*self._native_geom, level)
        if (probe.width, probe.height) == (gw, gh):
            self._degrade_level = level
        _M_MESH_REBUILDS.inc()
        obsev.emit("mesh-rebuild", point=f"{ns}x{nx}",
                   chips=len(surviving), level=level,
                   geometry=f"{probe.width}x{probe.height}")
        # the rebuilt step jit-compiles on its first tick; the liveness
        # probe must ride that out like any codec rebuild
        self._healthz_grace_until = time.monotonic() + 180.0
        log.warning("mesh rebuilt: (%d session x %d spatial) over %d "
                    "chips%s; recovery IDR queued for all sessions",
                    ns, nx, len(surviving),
                    f", degrade level {level}" if level else "")

    def _rebucket_target(self, level: int, verbose: bool = True):
        """``(w, h)`` the bucket would serve at this rung, or None when
        the re-bucket cannot apply: already at that geometry, resizing
        disabled, or sessions not uniformly resizable (mixed raw sizes
        would degrade into DIFFERENT buckets, breaking the one-compiled-
        step invariant).  The backpressure path checks this BEFORE
        committing to a mesh rebuild — a refused re-bucket must not cost
        a recompile and a fleet-wide recovery IDR for zero capacity."""
        batch = self._batch
        nw, nh = self._native_geom
        w, h = batch.degraded_geometry(nw, nh, level)
        # uniformity is judged against the CURRENT bucket geometry, not
        # the native one — after a first rebucket the sources sit at the
        # previous degrade level and must still be eligible for the next
        cur = (self._probe.width, self._probe.height)
        if (w, h) == cur:
            return None
        if not self.cfg.webrtc_enable_resize:
            if verbose:
                log.warning("degrade level %d wants %dx%d but "
                            "WEBRTC_ENABLE_RESIZE is off; keeping "
                            "current geometry", level, w, h)
            return None
        if not all(hasattr(s, "resize") for s in self.sources) or any(
                (s.width, s.height) != cur for s in self.sources):
            if verbose:
                log.warning("sessions not uniformly resizable; keeping "
                            "current geometry")
            return None
        return (w, h)

    def _maybe_rebucket_geometry(self, level: int) -> None:
        """Shed resolution through the MB-snapped degrade ladder so the
        survivors carry the extra sessions-per-chip within budget (see
        :meth:`_rebucket_target` for when this refuses)."""
        target = self._rebucket_target(level)
        if target is None:
            return
        w, h = target
        nw, nh = self._native_geom
        log.warning("re-bucketing geometry %dx%d -> %dx%d (degrade "
                    "level %d)", self._probe.width, self._probe.height,
                    w, h, level)
        for src in self.sources:
            src.resize(w, h)
        probe = H264Encoder(w, h, qp=self.cfg.encoder_qp, mode="cavlc")
        self._probe = probe
        self._hub_probes = [probe] * len(self.sources)
        # measured us/MB must be attributed to the NEW bucket geometry
        self._set_ledger_context()
        nals = split_annexb(probe.headers())
        sps = next(n for n in nals if (n[0] & 0x1F) == 7)
        pps = next(n for n in nals if (n[0] & 0x1F) == 8)
        self.headers = probe.headers()
        self._hub_headers = [probe.headers()] * len(self.hubs)
        for hub in self.hubs:
            items = hub.rebucket(sps, pps)   # muxer swap: encode thread
            if self.loop is not None:        # client announce: loop
                self.loop.call_soon_threadsafe(
                    hub._subscribers.broadcast_all, items)
            else:
                hub._subscribers.broadcast_all(items)

    def _post(self, hub: SessionHub, fragment: bytes,
              keyframe: bool, fid: int = 0) -> None:
        if self.loop is not None:
            self.loop.call_soon_threadsafe(hub.publish, fragment,
                                           keyframe, fid)
        else:
            hub.publish(fragment, keyframe, fid)


class BucketedStreamManager:
    """Mixed-geometry multi-session serving (SURVEY.md §7 M5 hard part #3).

    XLA compiles one program per shape, so sessions are BUCKETED by their
    MB-padded geometry: every bucket gets its own
    :class:`BatchStreamManager` (its own compiled step and encode loop);
    sessions whose raw sizes pad to the same (pad_h, pad_w) share a bucket
    and differ only in their SPS crop window.  The device serializes the
    buckets' dispatches, so capacity is shared rather than partitioned.

    Global session indices keep their order across buckets — the
    ``/ws?session=i`` contract is unchanged."""

    def __init__(self, cfg: Config, sources: List, loop=None,
                 injectors: Optional[List] = None):
        from ..utils.mathutil import round_up

        injectors = injectors or [None] * len(sources)
        order = {}                      # (pad_h, pad_w) -> [global idx]
        for i, s in enumerate(sources):
            key = (round_up(s.height, 16), round_up(s.width, 16))
            order.setdefault(key, []).append(i)
        self.managers = []
        self._hub_of = {}               # global idx -> (manager, local idx)
        for key, idxs in order.items():
            mgr = BatchStreamManager(
                cfg, [sources[i] for i in idxs], loop=loop,
                injectors=[injectors[i] for i in idxs])
            for local, gi in enumerate(idxs):
                self._hub_of[gi] = (mgr, local)
            self.managers.append(mgr)
        log.info("bucketed %d sessions into %d geometry buckets: %s",
                 len(sources), len(self.managers),
                 {f"{k[1]}x{k[0]}": len(v) for k, v in order.items()})

    def session(self, idx: int):
        ent = self._hub_of.get(idx)
        return ent[0].session(ent[1]) if ent else None

    def start(self) -> None:
        for m in self.managers:
            m.start()

    def stop(self) -> None:
        for m in self.managers:
            m.stop()

    def close(self) -> None:
        for m in self.managers:
            m.close()

    def request_degrade_level(self, level: int) -> None:
        """Fleet backpressure applies to every bucket at once: degrading
        one bucket would punish its sessions without relieving the
        shared device (the dispatches serialize across buckets)."""
        for m in self.managers:
            m.request_degrade_level(level)

    def surviving_chips(self) -> int:
        # buckets share ONE device pool; the stalest view is the truth
        return min(m.surviving_chips() for m in self.managers)

    def applied_degrade_level(self) -> int:
        # conservative across buckets: the bucket still at the highest
        # quality bounds how much capacity degradation really freed
        return min(m.applied_degrade_level() for m in self.managers)

    def stats_summary(self) -> dict:
        # report sessions in GLOBAL index order (the /ws?session=i
        # numbering), not bucket order — monitoring must agree with serving
        per = {id(m): m.stats_summary() for m in self.managers}
        sessions = []
        for gi in sorted(self._hub_of):
            mgr, local = self._hub_of[gi]
            entry = dict(per[id(mgr)]["sessions"][local])
            entry["session"] = gi
            sessions.append(entry)
        return {"sessions": sessions,
                "buckets": [{"mesh": p["mesh"],
                             "sessions": len(p["sessions"])}
                            for p in per.values()]}

    # healthz liveness: the freshest bucket tick counts as progress only
    # if EVERY bucket is alive; report the stalest.
    @property
    def _last_tick(self):
        return min(m._last_tick for m in self.managers)

    @property
    def _healthz_grace_until(self):
        return max(m._healthz_grace_until for m in self.managers)
