"""Deterministic, seeded network-impairment shim for the loopback path.

The chaos bench (web/chaos) proves loss recovery against the REAL
packet machinery (webrtc/feedback), but real UDP loss is neither
reproducible nor CI-friendly.  :class:`ImpairedLink` sits between a
sender's ``transmit`` callback and a receiver's ``on_packet`` and
applies the classic netem vocabulary — random loss, scripted burst
loss, jitter, reordering, and a bandwidth cap — from one seeded RNG,
so the same seed always drops the same packets in the same places.

Two driving modes share one implementation:

- **manual** (unit tests): call :meth:`pump` with a fake ``now`` — the
  due queue releases deterministically against the injected clock.
- **asyncio** (chaos bench): :meth:`start` runs a small pump task on
  the event loop at ``tick_s`` granularity.

The ``rtp_loss_burst`` fault point (resilience/faults) fires HERE —
arming it swallows the next N packets through the link exactly where a
congested bottleneck queue would tail-drop them.
"""

from __future__ import annotations

import heapq
import random
import time
from typing import Callable, Optional

from ..resilience import faults as rfaults

__all__ = ["ImpairedLink"]


class ImpairedLink:
    """One direction of an impaired wire.

    Parameters mirror ``tc netem``: ``loss`` (0..1 independent drop
    probability), ``jitter_ms`` (uniform extra delay), ``reorder``
    (0..1 probability a packet gets jitter*2 extra delay and leaves
    after its successors), ``bandwidth_bps`` (serialization cap: each
    packet occupies the link for ``bytes*8/rate`` seconds; the backlog
    is bounded by ``max_backlog_bytes`` with tail drop, like a real
    bottleneck queue)."""

    def __init__(self, deliver: Callable[[bytes], None], *,
                 seed: int = 0,
                 loss: float = 0.0,
                 jitter_ms: float = 0.0,
                 reorder: float = 0.0,
                 bandwidth_bps: Optional[float] = None,
                 max_backlog_bytes: int = 256 * 1024,
                 tick_s: float = 0.002,
                 clock: Callable[[], float] = time.perf_counter):
        self.deliver = deliver
        self.loss = float(loss)
        self.jitter_ms = float(jitter_ms)
        self.reorder = float(reorder)
        self.bandwidth_bps = bandwidth_bps
        self.max_backlog_bytes = int(max_backlog_bytes)
        self.tick_s = float(tick_s)
        self._rng = random.Random(seed)
        self._clock = clock
        self._heap: list = []            # (release_t, order, pkt)
        self._order = 0
        self._bw_cursor = 0.0            # link-busy-until time
        self._backlog = 0
        self._burst_left = 0
        self._task = None
        self._closed = False
        self.sent = 0
        self.delivered = 0
        self.dropped = 0
        self.burst_dropped = 0
        self.bw_dropped = 0
        self.reordered = 0

    # -- controls ------------------------------------------------------

    def start_burst(self, n: int) -> None:
        """Drop the next ``n`` packets (scripted burst loss)."""
        self._burst_left = max(self._burst_left, int(n))

    def set_bandwidth(self, bps: Optional[float]) -> None:
        """(Un)cap the link.  Lifting the cap re-schedules every
        queued packet to NOW (a real bottleneck's queue drains at the
        new line rate — effectively instantly when uncapped), so the
        backlog genuinely flushes on the next pump."""
        self.bandwidth_bps = bps
        if bps is None:
            now = self._clock()
            self._heap = [(min(r, now), o, p, b)
                          for (r, o, p, b) in self._heap]
            heapq.heapify(self._heap)
            self._bw_cursor = 0.0

    # -- ingress -------------------------------------------------------

    def send(self, pkt: bytes, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        self.sent += 1
        # injected burst loss: the canonical rtp_loss_burst point fires
        # at the exact spot a bottleneck tail-drop would
        spec = rfaults.fire("rtp_loss_burst")
        if spec is not None:
            self.start_burst(int(spec.get("packets", 4)))
        if self._burst_left > 0:
            self._burst_left -= 1
            self.dropped += 1
            self.burst_dropped += 1
            return
        if self.loss > 0 and self._rng.random() < self.loss:
            self.dropped += 1
            return
        release = now
        bw_counted = False
        if self.bandwidth_bps:
            if self._backlog + len(pkt) > self.max_backlog_bytes:
                self.dropped += 1
                self.bw_dropped += 1
                return
            busy_from = max(self._bw_cursor, now)
            self._bw_cursor = busy_from + len(pkt) * 8.0 \
                / self.bandwidth_bps
            release = self._bw_cursor
            self._backlog += len(pkt)
            bw_counted = True
        if self.jitter_ms > 0:
            release += self._rng.uniform(0.0, self.jitter_ms) / 1e3
        if self.reorder > 0 and self._rng.random() < self.reorder:
            release += self.jitter_ms * 2.0 / 1e3 + 1e-4
            self.reordered += 1
        self._order += 1
        heapq.heappush(self._heap, (release, self._order, pkt,
                                    bw_counted))
        self.pump(now)

    # -- egress --------------------------------------------------------

    def pump(self, now: Optional[float] = None) -> int:
        """Deliver everything due by ``now``; returns the count."""
        now = self._clock() if now is None else now
        n = 0
        while self._heap and self._heap[0][0] <= now:
            _, _, pkt, bw_counted = heapq.heappop(self._heap)
            if bw_counted:    # release its share of the bounded queue
                self._backlog = max(0, self._backlog - len(pkt))
            self.delivered += 1
            n += 1
            self.deliver(pkt)
        return n

    def pending(self) -> int:
        return len(self._heap)

    def flush(self) -> int:
        """Deliver everything regardless of release time (teardown)."""
        n = 0
        while self._heap:
            _, _, pkt, _ = heapq.heappop(self._heap)
            self.delivered += 1
            n += 1
            self.deliver(pkt)
        self._backlog = 0
        return n

    # -- asyncio driver ------------------------------------------------

    def start(self, loop=None) -> None:
        """Run the pump on the event loop (chaos-bench mode)."""
        import asyncio

        if self._task is not None:
            return
        loop = loop if loop is not None else asyncio.get_running_loop()
        self._task = loop.create_task(self._run())

    async def _run(self) -> None:
        import asyncio

        try:
            while not self._closed:
                self.pump()
                await asyncio.sleep(self.tick_s)
        except asyncio.CancelledError:
            pass

    def close(self) -> None:
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def stats(self) -> dict:
        return {
            "sent": self.sent,
            "delivered": self.delivered,
            "dropped": self.dropped,
            "burst_dropped": self.burst_dropped,
            "bw_dropped": self.bw_dropped,
            "reordered": self.reordered,
            "pending": self.pending(),
        }
