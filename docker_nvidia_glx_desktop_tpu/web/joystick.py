"""Joystick hub: browser Gamepad events -> the C interposer's sockets.

Counterpart of ``native/joystick_interposer.c`` (reference Dockerfile:473-476
/ E10): listens on ``$JOYSTICK_SOCKET_DIR/jsN`` unix sockets; every game
process that opens ``/dev/input/jsN`` through the LD_PRELOAD shim becomes a
subscriber, and each web-client gamepad message is fanned out as a
``struct js_event`` (``__u32 time; __s16 value; __u8 type; __u8 number``).

Wire protocol (extends web/input.py):
  ``ja,<axis>,<value>``   axis position, value in [-1.0, 1.0]
  ``jb,<button>,<0|1>``   button press/release
"""

from __future__ import annotations

import asyncio
import logging
import os
import struct
import time
from typing import List, Optional

log = logging.getLogger(__name__)

__all__ = ["JoystickHub", "parse_js_message"]

JS_EVENT_BUTTON = 0x01
JS_EVENT_AXIS = 0x02
JS_EVENT_INIT = 0x80


def parse_js_message(msg: str) -> Optional[dict]:
    parts = msg.strip().split(",")
    try:
        if parts[0] == "ja":
            return {"type": "axis", "number": int(parts[1]),
                    "value": max(-1.0, min(1.0, float(parts[2])))}
        if parts[0] == "jb":
            return {"type": "button", "number": int(parts[1]),
                    "down": parts[2] == "1"}
    except (IndexError, ValueError):
        pass
    return None


class JoystickHub:
    """Unix-socket server fanning js_events out to interposed game fds."""

    def __init__(self, socket_dir: Optional[str] = None, index: int = 0):
        self.socket_dir = socket_dir or os.environ.get(
            "JOYSTICK_SOCKET_DIR", "/tmp/joystick")
        self.index = index
        self._writers: List[asyncio.StreamWriter] = []
        self._server: Optional[asyncio.AbstractServer] = None
        self._t0 = time.monotonic()

    @property
    def path(self) -> str:
        return os.path.join(self.socket_dir, f"js{self.index}")

    async def start(self) -> None:
        os.makedirs(self.socket_dir, exist_ok=True)
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass
        self._server = await asyncio.start_unix_server(
            self._on_connect, path=self.path)

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for w in self._writers:
            w.close()
        self._writers.clear()

    async def _on_connect(self, reader, writer) -> None:
        # Synthetic init events announce current state (kernel js API does
        # the same with JS_EVENT_INIT on open).
        for a in range(8):
            writer.write(self._pack(JS_EVENT_AXIS | JS_EVENT_INIT, a, 0))
        for b in range(16):
            writer.write(self._pack(JS_EVENT_BUTTON | JS_EVENT_INIT, b, 0))
        try:
            await writer.drain()
        except ConnectionError:
            return
        self._writers.append(writer)
        try:
            await reader.read()        # until the game closes the fd
        finally:
            if writer in self._writers:
                self._writers.remove(writer)
            writer.close()

    def _pack(self, etype: int, number: int, value: int) -> bytes:
        ms = int((time.monotonic() - self._t0) * 1000) & 0xFFFFFFFF
        return struct.pack("<IhBB", ms, value, etype, number)

    def handle(self, event: dict) -> None:
        if event["type"] == "axis":
            data = self._pack(JS_EVENT_AXIS, event["number"],
                              int(event["value"] * 32767))
        elif event["type"] == "button":
            data = self._pack(JS_EVENT_BUTTON, event["number"],
                              1 if event["down"] else 0)
        else:
            return
        for w in list(self._writers):
            try:
                w.write(data)
            except ConnectionError:
                self._writers.remove(w)

    def handle_message(self, msg: str) -> Optional[dict]:
        event = parse_js_message(msg)
        if event is not None:
            self.handle(event)
        return event
