"""Web delivery layer: signaling/streaming server, input injection, MP4
packaging — the first-party rebuild of the selkies-gstreamer role
(reference Dockerfile:410-476, selkies-gstreamer-entrypoint.sh)."""
