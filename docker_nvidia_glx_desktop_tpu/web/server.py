"""First-party streaming web server — the selkies-gstreamer role.

One aiohttp application on the single exposed port (8080, reference
Dockerfile:535) provides everything the reference's web layer does
(selkies-gstreamer-entrypoint.sh:43-47):

- **HTTP basic auth** on every route when ``ENABLE_BASIC_AUTH`` (password
  chain ``BASIC_AUTH_PASSWORD <- PASSWD``, selkies-gstreamer-entrypoint.sh:20);
- **/** the built-in web client (MSE player + input capture);
- **/manifest.json** PWA manifest honoring ``PWA_APP_NAME``/``PWA_APP_SHORT_NAME``/
  ``PWA_START_URL`` (the manifest-rewrite parity, selkies-gstreamer-entrypoint.sh:27-38);
- **/turn** RTCConfiguration JSON (TURN REST-API credentials, ``web/turn.py``);
- **/stats** live session metrics (fps, encode-ms percentiles, bitrate —
  SURVEY.md §5 observability parity) — a JSON view over the obs registry;
- **/metrics** Prometheus text exposition (incl. the ``slo_*`` gauges
  evaluating the BASELINE ladder), **/debug/trace** Chrome trace-event
  JSON of the per-frame pipeline ring buffer, and **/debug/budget** the
  serving-budget ledger with link-separated per-stage p50s and SLO
  verdicts (``obs/``); all auth-exempt like ``/healthz``;
- **/ws** the session websocket: JSON control messages down, binary fMP4
  media down, compact input messages up (``web/input.py`` protocol).

HTTPS via ``ENABLE_HTTPS_WEB``/``HTTPS_WEB_CERT``/``HTTPS_WEB_KEY``
(xgl.yml:68-74).  The media transport is MSE-over-WebSocket — TPU-encoded
H.264 in fMP4 fragments — which needs no GStreamer/SRTP on either end; the
signaling surface (SDP offer/answer message types) is kept so a webrtcbin
bridge can slot in where GStreamer exists.
"""

from __future__ import annotations

import base64
import hmac
import importlib.resources
import json
import logging
import ssl
import time
from typing import Optional

from aiohttp import WSMsgType, web

from ..obs.http import OBS_EXEMPT_PATHS, add_obs_routes
from ..obs.metrics import REGISTRY
# Imported for the metric-registration side effect: the dngd_sctp_* /
# dngd_datachannel_* families (and the sctp_drop_burst/dcep_open_stall
# fault points) must exist on /metrics from server start — a dashboard
# watching retransmits cannot wait for the first stock client to
# connect.  Deliberately NOT webrtc.peer: that pulls in dtls, which
# dlopens libssl.so.3 and must stay lazy for libssl-less images.
from ..webrtc import datachannel as _datachannel  # noqa: F401
from ..webrtc import sctp as _sctp  # noqa: F401
# Same PR-13 lesson for the content & quality plane: the dngd_content_*
# families and the psnr_floor_breach/damage_spike event-kind series
# register at import (plus the flight-recorder state provider), so
# /metrics and /debug/events carry them from boot, not first frame.
from ..obs import content as _content  # noqa: F401
# ... and for the client-QoE gauges (dngd_client_qoe_*), which would
# otherwise only register when the first stock client connects
from . import selkies_shim as _selkies  # noqa: F401
from ..resilience import faults as rfaults
# Ingress governor: imported eagerly so the dngd_ingress_* violation /
# quarantine families exist on /metrics from boot (same boot-visibility
# lesson), and used per-connection below (PeerBudget / ProbeWindow).
from ..resilience import ingress as ringress
# Handoff plane: eager so the dngd_handoff_* families are scrape-
# visible from boot (the successor's CI smoke asserts them on /metrics
# before any client resumes), and used below for drain-to-migrate.
from ..resilience import handoff as rhandoff
from ..resilience.continuity import DrainState
from ..utils.config import Config
from .input import Injector, make_injector
from .turn import ice_servers

log = logging.getLogger(__name__)

__all__ = ["make_app", "serve", "basic_auth_middleware",
           "handle_input_text", "spawn_bg"]

# Strong refs to fire-and-forget tasks (shed-eviction notifies): the
# event loop keeps only a weak reference to scheduled tasks, so a bare
# ensure_future can be garbage-collected mid-flight and the eviction
# close never reaches the client (analysis finding async-task-leak).
_BG_TASKS: set = set()


def spawn_bg(coro):
    import asyncio

    task = asyncio.ensure_future(coro)
    _BG_TASKS.add(task)
    task.add_done_callback(_BG_TASKS.discard)
    return task


_spawn_bg = spawn_bg     # data-channel binders (selkies_shim) share it


def basic_auth_middleware(cfg: Config):
    """401-challenge everything unless the basic-auth password matches.
    Any username is accepted — the reference authenticates by password only
    (README.md:23: the selkies login is PASSWD with user ignored)."""

    expected = cfg.effective_basic_auth_password

    @web.middleware
    async def mw(request: web.Request, handler):
        # k8s probes, Prometheus scrapers and trace pulls run without the
        # session password (same contract as the reference's probes).
        # READ-ONLY methods only: the exemption is for telemetry, and
        # /debug/faults carries a state-mutating POST (arming a fault)
        # that must clear BOTH the DNGD_FAULT_INJECTION gate and auth.
        if request.method in ("GET", "HEAD") and (
                request.path == "/healthz"
                or request.path in OBS_EXEMPT_PATHS):
            return await handler(request)
        if not cfg.enable_basic_auth:
            return await handler(request)
        hdr = request.headers.get("Authorization", "")
        ok = False
        if hdr.startswith("Basic "):
            try:
                decoded = base64.b64decode(hdr[6:]).decode()
                _, _, password = decoded.partition(":")
                ok = hmac.compare_digest(password, expected)
            except Exception:
                ok = False
        if not ok:
            return web.Response(
                status=401,
                headers={"WWW-Authenticate":
                         'Basic realm="tpu-desktop", charset="UTF-8"'})
        return await handler(request)

    return mw


def _client_html(cfg: Config) -> str:
    try:
        return (importlib.resources.files(__package__)
                .joinpath("static/index.html").read_text())
    except Exception:
        return "<html><body>client assets missing</body></html>"


def make_app(cfg: Config, session=None,
             injector: Optional[Injector] = None,
             supervisor=None, joystick=None,
             audio=None, manager=None) -> web.Application:
    app = web.Application(middlewares=[basic_auth_middleware(cfg)])
    # In manager (multi-session) mode input routing is per-hub; a global
    # injector would open a second uinput/X connection that nothing uses.
    if injector is None and manager is None:
        injector = make_injector(cfg.display)

    # SLO-driven degradation ladder (resilience/degrade): reacts to the
    # serving-budget ledger + per-peer RTCP loss by shedding quality
    # through the session's own control paths.  DEGRADE_ENABLE=false
    # (or no session to execute on) leaves the controller off.
    app["degrade"] = None
    # Single-session only: a batched manager shares one device budget
    # across N sessions, and degrading only hub 0 would punish one
    # client without relieving the breach — a manager-level executor
    # (degrade the whole bucket, re-bucket via batch.degraded_geometry)
    # is the follow-up, not a session(0) special case.
    degrade_target = session
    if manager is not None and cfg.degrade_enable:
        log.info("degradation ladder not wired in multi-session mode "
                 "(needs a manager-level executor)")
    if cfg.degrade_enable and degrade_target is not None:
        from ..resilience.degrade import DegradeController, SessionExecutor

        ctl = DegradeController(SessionExecutor(degrade_target, cfg=cfg))
        app["degrade"] = ctl

        async def _start_degrade(app_):
            import asyncio

            app_["degrade_task"] = asyncio.ensure_future(
                ctl.run(cfg.degrade_interval_s))

        async def _stop_degrade(app_):
            ctl.stop()
            task = app_.get("degrade_task")
            if task is not None:
                task.cancel()

        app.on_startup.append(_start_degrade)
        app.on_cleanup.append(_stop_degrade)

    # -- fleet admission & overload protection (fleet/) ----------------
    # Capacity-aware scheduler between /ws and the managers: admit /
    # queue / reject-with-retry_after_s, queue-depth backpressure into
    # the degrade ladder fleet-wide, newest/lowest-tier-first shedding.
    app["fleet"] = None
    if cfg.fleet_enable:
        from ..fleet.capacity import CapacityModel
        from ..fleet.scheduler import FleetScheduler

        def _chips() -> int:
            if manager is not None and hasattr(manager, "surviving_chips"):
                return manager.surviving_chips()
            return 1

        def _fleet_degrade(level: int) -> None:
            # manager mode: MB-snapped geometry re-bucket (one shared
            # compiled step per rung, parallel/batch.DEGRADE_SCALES);
            # single-session mode: the PR 3 qp/fps executors directly —
            # but ONLY when the SLO DegradeController is off, because it
            # owns the same knobs and a backpressure restore here would
            # silently undo its engaged rung (overload surfaces as a
            # budget breach it already walks its own ladder for)
            if manager is not None:
                if hasattr(manager, "request_degrade_level"):
                    manager.request_degrade_level(level)
                return
            if session is None or app["degrade"] is not None:
                return
            from ..resilience.degrade import SessionExecutor
            if hasattr(session, "set_qp_offset"):
                session.set_qp_offset(
                    SessionExecutor.QP_STEP if level >= 1 else 0)
            if hasattr(session, "set_fps_cap"):
                session.set_fps_cap(
                    max(cfg.refresh / 2.0, 5.0) if level >= 2 else None)

        fleet = FleetScheduler(
            model=CapacityModel(
                max_sessions_override=cfg.fleet_max_sessions,
                per_chip_override=cfg.fleet_sessions_per_chip,
                tune=getattr(cfg, "encoder_tune", "off")),
            chips_fn=_chips,
            geometry=(cfg.sizew, cfg.sizeh), fps=cfg.refresh,
            queue_depth=cfg.fleet_queue_depth,
            queue_timeout_s=cfg.fleet_queue_timeout_s,
            retry_after_s=cfg.fleet_retry_after_s,
            on_degrade=_fleet_degrade,
            max_degrade_level=cfg.fleet_backpressure_level,
            # only the batch managers' MB-snapped re-bucket actually
            # shrinks the serving geometry, and only with resize on;
            # the single-session qp/fps executors change cost, not MBs
            degrade_shrinks_geometry=(manager is not None
                                      and cfg.webrtc_enable_resize),
            # capacity follows the rung the mesh is ACTUALLY serving —
            # the manager may refuse a requested re-bucket
            applied_level_fn=(manager.applied_degrade_level
                              if manager is not None
                              and hasattr(manager, "applied_degrade_level")
                              else None))
        app["fleet"] = fleet
        # flight-recorder postmortems embed the live fleet picture
        from ..obs import flight as obsf
        obsf.register_state_provider("fleet", fleet.snapshot)

        async def _start_fleet(app_):
            import asyncio

            app_["fleet_task"] = asyncio.ensure_future(fleet.run(0.5))

        async def _stop_fleet(app_):
            fleet.stop()
            task = app_.get("fleet_task")
            if task is not None:
                task.cancel()

        app.on_startup.append(_start_fleet)
        app.on_cleanup.append(_stop_fleet)

    def resolve_session(request):
        """Single session, or ``?session=i`` into a BatchStreamManager;
        under fleet admission an unqualified join is assigned the
        least-loaded hub (the scheduler decides WHETHER, this decides
        WHERE)."""
        if manager is not None:
            q = request.query.get("session")
            if q is None and app["fleet"] is not None:
                best, best_n, i = None, None, 0
                while True:
                    hub = manager.session(i)
                    if hub is None:
                        break
                    n = len(hub._subscribers)
                    if best is None or n < best_n:
                        best, best_n = hub, n
                    i += 1
                return best
            try:
                idx = int(q or "0")
            except ValueError:
                return None
            return manager.session(idx)
        return session

    # -- graceful drain (SIGTERM / POST /debug/drain) ------------------
    # Draining flips one flag: new websocket sessions are refused with a
    # {"type": "draining"} answer, and every CONNECTED subscriber gets a
    # ("draining",) control item so its client can pre-connect elsewhere
    # while the last in-flight frames keep flushing.  The process exits
    # only when the caller (server_main's SIGTERM handler, or the k8s
    # preStop hook's sleep) decides the grace period is over.
    drain = DrainState()
    app["drain"] = drain

    def _drain_sessions():
        if manager is not None:      # Batch or Bucketed manager shapes
            mgrs = getattr(manager, "managers", None) or [manager]
            return [h for m in mgrs for h in getattr(m, "hubs", [])]
        return [session] if session is not None else []

    def begin_drain(reason: str = "drain") -> bool:
        fresh = drain.begin(reason)
        if fresh:
            from ..obs import events as obsev
            obsev.emit("drain", reason=reason)
            # a drain-initiated disconnect is a deploy, not an incident:
            # it lands in shed_total under its own reason label
            if app["fleet"] is not None:
                app["fleet"].account_drain("drain")
            for sess in _drain_sessions():
                subs = getattr(sess, "_subscribers", None)
                if subs is not None:
                    subs.broadcast_all([("draining", reason)])
        return fresh

    app["begin_drain"] = begin_drain

    # -- zero-downtime handoff (resilience/handoff) --------------------
    # With DNGD_HANDOFF_DIR (or _SOCK) set, drain MIGRATES instead of
    # shedding: snapshot encoder + wire continuity per connection, hand
    # it to the successor, tell each client to reconnect with a resume
    # token.  Without it, the legacy drain-and-shed above runs.
    hmgr = rhandoff.HandoffManager(
        handoff_dir=getattr(cfg, "handoff_dir", ""),
        sock_path=getattr(cfg, "handoff_sock", ""),
        token_ttl_s=getattr(cfg, "handoff_token_ttl_s", 45.0))
    app["handoff"] = hmgr

    def _adopt_imported(entries):
        """Queue imported encoder lineages onto this process's hubs
        (index-aligned with the predecessor's hub list); the encode
        threads adopt between frames."""
        hubs = _drain_sessions()
        for ent in entries or []:
            try:
                idx = int(ent.get("index") or 0)
            except (TypeError, ValueError):
                idx = 0
            if 0 <= idx < len(hubs) and \
                    hasattr(hubs[idx], "adopt_handoff"):
                hubs[idx].adopt_handoff(ent.get("state") or {})

    if hmgr.enabled:
        from ..obs import flight as obsf
        obsf.register_state_provider("handoff", hmgr.snapshot)
        # restart-in-place successor: consume whatever a predecessor
        # spooled before we started accepting /ws joins
        _adopt_imported(hmgr.load_spool())
        if hmgr.sock_path:
            async def _start_handoff_sock(app_):
                app_["handoff_sock_srv"] = await rhandoff.serve_socket(
                    hmgr, _adopt_imported)

            async def _stop_handoff_sock(app_):
                srv = app_.get("handoff_sock_srv")
                if srv is not None:
                    srv.close()

            app.on_startup.append(_start_handoff_sock)
            app.on_cleanup.append(_stop_handoff_sock)

    async def handoff_migrate(reason: str = "migrate") -> dict:
        """Drain-to-migrate: freeze the encode threads, export session
        + wire snapshots, spool/stream them, then hand every connected
        client its resume token.  A transfer failure falls back to the
        legacy shed — accounted as ``handoff_failed`` and flight-dumped
        (``handoff-failed`` is a trigger kind)."""
        import asyncio

        from ..obs import events as obsev

        if not hmgr.enabled:
            begin_drain(reason)
            return {"enabled": False, "migrated": 0}
        # refuse new joins, but QUIETLY: clients get migrate tokens
        # below, not the pre-connect-elsewhere shed broadcast
        if drain.begin(reason):
            obsev.emit("drain", reason=reason, mode="migrate")
        loop = asyncio.get_running_loop()
        hubs = _drain_sessions()
        t0 = time.monotonic()

        def _freeze_and_export():
            # export_state walks encoder internals: park the encode
            # threads first (stop() joins; this runs in the executor so
            # the event loop keeps serving in-flight sockets meanwhile)
            for h in hubs:
                try:
                    h.stop()
                except Exception:
                    log.exception("session stop failed during handoff")
            return hmgr.export(hubs)

        snapshot = await loop.run_in_executor(None, _freeze_and_export)
        try:
            if hmgr.sock_path:
                await rhandoff.send_over_socket(hmgr.sock_path, snapshot)
                dest = hmgr.sock_path
            else:
                dest = await loop.run_in_executor(
                    None, hmgr.spool, snapshot)
        except Exception as e:
            log.exception("handoff transfer failed; falling back to "
                          "legacy drain-and-shed")
            obsev.emit("handoff-failed", reason="transfer_error",
                       error=str(e))
            if app["fleet"] is not None:
                app["fleet"].account_drain("handoff_failed")
            for sess in hubs:
                subs = getattr(sess, "_subscribers", None)
                if subs is not None:
                    subs.broadcast_all([("draining", reason)])
            return {"enabled": True, "migrated": 0, "failed": True}
        notified = hmgr.notify_all(retry_after_s=0.5)
        obsev.emit("handoff-export",
                   sessions=len(snapshot["sessions"]),
                   conns=len(snapshot["conns"]), notified=notified,
                   dest=dest,
                   ms=round((time.monotonic() - t0) * 1e3, 1))
        return {"enabled": True, "migrated": len(snapshot["conns"]),
                "sessions": len(snapshot["sessions"]),
                "notified": notified, "dest": dest}

    app["handoff_migrate"] = handoff_migrate

    async def drain_handler(request):
        if hmgr.enabled:
            if drain.draining:           # idempotent like legacy drain
                body = drain.snapshot()
                body["initiated"] = False
                return web.json_response(body)
            result = await handoff_migrate("POST /debug/drain")
            body = drain.snapshot()
            body["initiated"] = True
            body["handoff"] = result
            return web.json_response(body)
        fresh = begin_drain("POST /debug/drain")
        body = drain.snapshot()
        body["initiated"] = fresh
        return web.json_response(body)

    async def drain_status(request):
        return web.json_response(drain.snapshot())

    async def handoff_status(request):
        return web.json_response(hmgr.snapshot())

    # Read once at app build (sync context): serving it from the async
    # handler re-read the file from disk per request on the event loop
    # (analysis finding async-blocking-call server.py/index).
    client_html = _client_html(cfg)

    async def index(request):
        return web.Response(text=client_html, content_type="text/html")

    async def manifest(request):
        return web.json_response({
            "name": cfg.pwa_app_name,
            "short_name": cfg.pwa_app_short_name,
            "start_url": cfg.pwa_start_url,
            "display": "standalone",
            "background_color": "#000000",
            "theme_color": "#000000",
        })

    async def service_worker(request):
        # PWA parity: the reference rewrites manifest AND service worker
        # (selkies-gstreamer-entrypoint.sh:27-38).  Network-first with an
        # offline shell fallback; cache name tracks the configured app so
        # renames invalidate stale shells.
        cache = f"tpu-desktop-{cfg.pwa_app_short_name}-v1".replace(" ", "-")
        js = (
            'const CACHE = %r;\n'
            'self.addEventListener("install", (e) => {\n'
            '  e.waitUntil(caches.open(CACHE).then(\n'
            '    (c) => c.addAll(["%s", "manifest.json"])));\n'
            '  self.skipWaiting();\n'
            '});\n'
            'self.addEventListener("activate", (e) => {\n'
            '  e.waitUntil(caches.keys().then((ks) => Promise.all(\n'
            '    ks.filter((k) => k !== CACHE)\n'
            '      .map((k) => caches.delete(k)))));\n'
            '});\n'
            'self.addEventListener("fetch", (e) => {\n'
            '  if (e.request.method !== "GET") return;\n'
            '  e.respondWith(fetch(e.request).catch(\n'
            '    () => caches.match(e.request)));\n'
            '});\n' % (cache, cfg.pwa_start_url))
        return web.Response(text=js, content_type="application/javascript")

    async def turn(request):
        return web.json_response(ice_servers(cfg))

    async def stats(request):
        if manager is not None:
            payload = manager.stats_summary()
        else:
            payload = {"session": (session.stats_summary()
                                   if session is not None else None)}
        if supervisor is not None:
            payload["programs"] = supervisor.status()
        # /stats is a JSON view over the same registry /metrics exposes
        # (one source of truth for dashboards and the web client alike)
        payload["metrics"] = REGISTRY.snapshot()
        # the serving-budget ledger (obs/budget): per-stage p50s with
        # link cost separated + SLO verdicts — the same shared emitter
        # /debug/budget?format=json renders and bench.py snapshots
        from ..obs.budget import serving_budget_block
        payload["serving_budget"] = serving_budget_block()
        if app["degrade"] is not None:
            payload["degrade"] = app["degrade"].snapshot()
        if app["fleet"] is not None:
            payload["fleet"] = app["fleet"].snapshot()
        return web.json_response(payload)

    async def ws_handler(request):
        import asyncio

        ws = web.WebSocketResponse(heartbeat=20.0, max_msg_size=0)
        await ws.prepare(request)
        if drain.draining:
            # stop admitting: the client gets an explicit reason (so it
            # can pre-connect to another replica) instead of a refused
            # socket it would retry against this same dying pod
            await ws.send_json({"type": "draining",
                                "reason": drain.reason or "drain"})
            await ws.close()
            return ws
        # handoff resume (resilience/handoff): a client carrying a
        # predecessor's resume token redeems it here — single-use,
        # TTL-bounded.  An unknown/expired token degrades to a normal
        # join (counted on dngd_handoff_resume_total), never a refusal.
        resume_entry = None
        resume_token = request.query.get("resume")
        if resume_token and hmgr.enabled:
            resume_entry = hmgr.claim(resume_token)
        # fleet admission: every join is admitted, queued (acquire
        # blocks up to the queue timeout), or cleanly rejected with a
        # retry_after_s the client backs off against — never a silent
        # hang, never an unexplained refusal.  A migrating-in session
        # bypasses both gates at its recorded tier: it already held a
        # slot on the predecessor.
        fleet = app["fleet"]
        adm = None
        if fleet is not None:
            if resume_entry is not None:
                try:
                    mtier = int(resume_entry.get("tier") or 0)
                except (TypeError, ValueError):
                    mtier = 0
                adm = fleet.admit_migration(tier=mtier)
            else:
                try:
                    tier = int(request.query.get("tier", "0"))
                except ValueError:
                    tier = 0
                adm = await fleet.acquire(tier=tier)
            if not adm.admitted:
                await ws.send_json(adm.payload())
                await ws.close()
                return ws
        sess = resolve_session(request)
        if sess is None:
            if adm is not None:
                fleet.release(adm)
            await ws.send_json({"type": "error",
                                "reason": "no active session"})
            await ws.close()
            return ws
        if adm is not None:
            # shedding path: the scheduler evicts THIS connection with a
            # busy/retry_after_s answer the client treats like any other
            # rejection (reconnect with jittered backoff; the hub keeps
            # its encoder checkpoint, so re-admission resumes the stream
            # from a recovery IDR — shed, not killed)
            def _evict(retry_after: float, _ws=ws) -> None:
                async def _go():
                    try:
                        await _ws.send_json({
                            "type": "busy", "reason": "shed",
                            "retry_after_s": round(retry_after, 2),
                            "reconnect": True})
                        await _ws.close()
                    except Exception:
                        pass
                _spawn_bg(_go())

            adm.evict = _evict
        # from here on the admission slot is held: EVERY exit — a client
        # that vanished mid-handshake included — must release it, or
        # churn slowly eats capacity with dead admissions
        try:
            hello = (sess.hello() if hasattr(sess, "hello") else
                     {"type": "hello", "codec": sess.codec_name,
                      "mime": getattr(sess, "mime",
                                      'video/mp4; codecs="avc1.42E01E"'),
                      "width": sess.source.width,
                      "height": sess.source.height})
            hello["audio"] = audio is not None
            # every connection joins the handoff set: the resume token
            # in the hello is what the client presents to the successor
            # if THIS process is the one that dies next
            handoff_token = None
            if hmgr.enabled:
                def _notify_migrate(tok, retry_s, _ws=ws):
                    async def _go():
                        try:
                            await _ws.send_json({
                                "type": "migrate", "resume": tok,
                                "retry_after_s": round(retry_s, 2)})
                        except Exception:
                            pass
                    _spawn_bg(_go())

                handoff_token = hmgr.register(
                    sid=(adm.sid if adm is not None
                         else f"ws-{request.remote or 'local'}"),
                    tier=(adm.tier if adm is not None else 0),
                    notify=_notify_migrate)
                hello["resume"] = handoff_token
            if resume_entry is not None:
                hello["resumed"] = True
                from ..obs import events as obsev
                obsev.emit("handoff-resume",
                           session=resume_entry.get("sid"),
                           tier=resume_entry.get("tier"))
            await ws.send_json(hello)
            if resume_entry is not None and hasattr(sess, "request_idr"):
                # exactly one recovery IDR on resume: the rate-limited
                # request_idr dedupes a reconnect storm into one grant
                sess.request_idr("handoff")
            # Per-hub injectors prevent cross-session input leaks: a
            # client on a synthetic session must not drive session 0's
            # real desktop.
            sess_injector = getattr(sess, "injector", None)
            if sess_injector is None and manager is None:
                sess_injector = injector
            queue = sess.subscribe()
            # trust boundary (resilience/ingress): one abuse governor +
            # one outstanding-probe window per connection.  EVICT rides
            # the same busy/shed payload as scheduler shedding (without
            # the reconnect invitation); the "shed" event the budget
            # emits on the way dumps the flight recorder.
            probes = ringress.ProbeWindow()

            def _ingress_evict(bud, reason, _ws=ws):
                async def _go():
                    try:
                        await _ws.send_json({
                            "type": "busy", "reason": "shed",
                            "retry_after_s": 30.0, "reconnect": False})
                        await _ws.close()
                    except Exception:
                        pass
                _spawn_bg(_go())

            budget = ringress.PeerBudget(
                f"ws-{request.remote or 'local'}",
                on_evict=_ingress_evict)
            sender = asyncio.ensure_future(_pump_media(ws, queue, probes))
            loop = asyncio.get_running_loop()
            # per-connection state: WebRTC peer + taps, MSE queue handle
            sockname = (request.transport.get_extra_info("sockname")
                        if request.transport is not None else None)
            from .turn import server_turn_config
            conn = {"peer": None, "on_au": None, "on_audio": None,
                    "queue": queue, "audio": audio,
                    "budget": budget, "probes": probes,
                    "injector": sess_injector,
                    "advertise_ip": (sockname[0] if sockname
                                     else "127.0.0.1"),
                    "turn": server_turn_config(cfg),
                    # the client's address as this server sees it — a
                    # TURN permission for it covers the common NAT case
                    # even before any trickled candidates arrive
                    "client_ip": request.remote,
                    # wire continuity from the predecessor's peer (same
                    # SSRC / seq frontier / ROC / SCTP counters), applied
                    # to the successor peer before its offer is answered
                    "resume_wire": (resume_entry or {}).get("wire"),
                    # once a peer exists, its wire exporter registers
                    # under this connection's token so a FUTURE migrate
                    # snapshots it
                    "handoff_attach": (
                        (lambda fn, _t=handoff_token:
                         hmgr.attach_wire(_t, fn))
                        if handoff_token is not None else None)}
            try:
                async for msg in ws:
                    if msg.type == WSMsgType.TEXT:
                        if joystick is not None and msg.data.startswith("j"):
                            joystick.handle_message(msg.data)
                            continue
                        await _handle_client_msg(msg.data, ws, sess,
                                                 sess_injector, loop, conn)
                    elif msg.type in (WSMsgType.CLOSE, WSMsgType.ERROR):
                        break
            finally:
                if handoff_token is not None:
                    # a connection that closes normally is NOT migrated;
                    # one closing because migrate() just notified it has
                    # already been snapshotted — detach is accounting
                    # either way
                    hmgr.detach(handoff_token)
                _teardown_peer(conn, sess)
                sess.unsubscribe(queue)
                sender.cancel()
                budget.close()
        finally:
            if adm is not None:
                # slot freed -> the scheduler promotes the next queued
                # joiner (an evicted session releases here too, once its
                # socket close lands)
                fleet.release(adm)
        return ws

    async def audio_handler(request):
        import asyncio

        ws = web.WebSocketResponse(heartbeat=20.0, max_msg_size=0)
        await ws.prepare(request)
        if drain.draining:
            # same admission gate as /ws: a draining pod must not bind
            # a fresh audio track it will drop within the grace window
            await ws.send_json({"type": "draining",
                                "reason": drain.reason or "drain"})
            await ws.close()
            return ws
        if audio is None:
            await ws.send_json({"type": "error", "reason": "no audio"})
            await ws.close()
            return ws
        await ws.send_json(audio.header)
        queue = audio.subscribe()

        async def pump():
            try:
                while True:
                    await ws.send_bytes(await queue.get())
            except (ConnectionError, asyncio.CancelledError):
                pass

        sender = asyncio.ensure_future(pump())
        try:
            # Drain incoming frames so the close handshake is processed —
            # a send-only handler would hang the client's close forever.
            async for _ in ws:
                pass
        finally:
            sender.cancel()
            audio.unsubscribe(queue)
        return ws

    # A wedged device RPC leaves the encode thread alive but frameless —
    # the exact failure a liveness probe must catch on a tunnel/flaky
    # interconnect — so health = thread alive AND frames not stale.
    # (Before the first frame the codec may still be jit-compiling;
    # that window is covered by the probe's initialDelaySeconds.)
    # HEALTHZ_STALL_S; default 30 s — the reference's noVNC heartbeat
    # is 10 s (entrypoint.sh:124).
    STALL_S = cfg.healthz_stall_s

    def _loop_healthy(obj, stats) -> bool:
        import time as _time

        thread = getattr(obj, "_thread", None)
        if thread is not None and not thread.is_alive():
            return False
        # A fresh codec build may be jit-compiling for longer than the
        # stall threshold (e.g. right after a resize): grace period.
        if _time.monotonic() < getattr(obj, "_healthz_grace_until", 0.0):
            return True
        # Prefer the loop's progress tick (refreshed on frame delivery
        # and on legitimate idleness, but NOT while spinning on encode
        # failures or wedged inside a device RPC).
        tick = getattr(obj, "_last_tick", None)
        if tick is not None and thread is not None:
            return (_time.monotonic() - tick) <= STALL_S
        if stats is not None and thread is not None:
            age = stats.last_frame_age_s()
            if age is not None and age > STALL_S:
                return False
        return True

    async def clipboard(request):
        """Desktop clipboard -> client (GET); runs xclip off-loop."""
        import asyncio as aio

        if injector is None:
            return web.json_response({"text": None})
        loop = aio.get_running_loop()
        text = await loop.run_in_executor(None, injector.read_clipboard)
        return web.json_response({"text": text})

    async def healthz(request):
        """Liveness with a degraded/unhealthy distinction (ISSUE 3):
        a pod shedding load through the degradation ladder is doing its
        JOB — it answers 200 with ``state: "degraded"`` so a K8s
        liveness probe never kills it for degrading correctly; only a
        genuinely wedged loop (stalled frames, dead thread) answers
        503 ``unhealthy``.  A FULL pod (fleet admission at capacity,
        ISSUE 6) is likewise healthy — 200 ``state: "at_capacity"`` so
        a capacity-aware balancer can route new joins elsewhere without
        liveness ever killing a pod for being popular."""
        healthy = True
        if manager is not None:
            # one encode thread feeds every hub; any hub's stats show it
            hub = manager.session(0)
            healthy = _loop_healthy(manager,
                                    getattr(hub, "stats", None))
        elif session is not None:
            healthy = _loop_healthy(session,
                                    getattr(session, "stats", None))
        ctl = app["degrade"]
        degraded = ctl is not None and ctl.level > 0
        fleet = app["fleet"]
        at_capacity = fleet is not None and fleet.at_capacity
        # draining stays 200: the pod is doing its job (flushing) and
        # liveness must not kill it before the grace period; the state
        # field lets a readiness-aware probe pull it from the Service
        state = ("unhealthy" if not healthy
                 else "draining" if drain.draining
                 else "at_capacity" if at_capacity
                 else "degraded" if degraded else "ok")
        body = {"ok": healthy, "state": state}
        if degraded:
            body["degrade"] = {"level": ctl.level, "step": ctl.step_name}
        if at_capacity:
            body["fleet"] = {"active": fleet.active,
                             "capacity": fleet.capacity,
                             "queued": fleet.queued,
                             "retry_after_s": round(
                                 fleet.retry_after_s(), 2)}
        return web.json_response(body, status=200 if healthy else 503)

    async def fleet_status(request):
        """``/debug/fleet``: the admission scheduler's live picture —
        capacity model inputs, active/queued sessions, backpressure
        level, shed/migration counts.  Text by default, ``?format=json``
        for the structured block (same shape the fleet bench reports)."""
        fleet = app["fleet"]
        if fleet is None:
            return web.json_response({"enabled": False})
        if request.query.get("format") == "json":
            snap = fleet.snapshot()
            snap["enabled"] = True
            return web.json_response(snap)
        from ..fleet.scheduler import render_fleet_text
        return web.Response(text=render_fleet_text(fleet),
                            content_type="text/plain")

    app.router.add_get("/", index)
    app.router.add_get("/index.html", index)
    app.router.add_get("/manifest.json", manifest)
    app.router.add_get("/sw.js", service_worker)
    app.router.add_get("/turn", turn)
    app.router.add_get("/stats", stats)
    app.router.add_get("/clipboard", clipboard)
    app.router.add_get("/healthz", healthz)
    add_obs_routes(app)                  # /metrics + /debug/trace
    rfaults.add_fault_routes(app)        # /debug/faults (POST env-gated)
    # graceful drain: GET = status, POST = initiate (behind basic auth
    # like every state-mutating route; the k8s preStop hook carries the
    # credential — see deploy/xgl-tpu.yml)
    app.router.add_get("/debug/drain", drain_status)
    app.router.add_post("/debug/drain", drain_handler)
    # handoff status (read-only): live registrations, pending resume
    # tokens, export/import/failure counts
    app.router.add_get("/debug/handoff", handoff_status)
    # fleet admission report (read-only, auth-exempt like /debug/budget)
    app.router.add_get("/debug/fleet", fleet_status)
    app.router.add_get("/ws", ws_handler)
    app.router.add_get("/audio", audio_handler)
    if session is not None:
        # stock selkies web-client signaling (role-inverted offer flow;
        # the shared injector feeds its SCTP input channels)
        from .selkies_shim import register_selkies_routes
        register_selkies_routes(app, cfg, session, audio,
                                injector=injector)
    return app


async def _pump_media(ws: web.WebSocketResponse, queue,
                      probes=None) -> None:
    import asyncio

    from ..obs import journey as obsj

    try:
        while True:
            item = await queue.get()  # ("kind", data[, keyframe[, fid]])
            kind, data = item[0], item[1]
            spec = rfaults.fire("ws_send_stall")
            if spec is not None:
                # simulated wedged client/socket: the queue behind this
                # pump fills, exercising eviction + slow-subscriber
                # eviction exactly as a real stall would
                await asyncio.sleep(
                    float(spec.get("delay_ms", 1000.0)) / 1e3)
            if kind == "evicted":
                # SubscriberSet gave up on this queue (sustained slow
                # streak); tell the client why, then close — reconnect
                # is immediate and re-admits with a fresh IDR-gated queue
                await ws.send_json({"type": "evicted", "reason": data,
                                    "reconnect": True})
                await ws.close()
                return
            if kind == "draining":
                # the server is going away: advise the client to pre-
                # connect elsewhere, but KEEP this socket flushing —
                # in-flight frames deliver until the process exits
                await ws.send_json({"type": "draining", "reason": data})
                continue
            if kind == "json":            # mid-stream control (e.g. resize)
                await ws.send_json(data)
            else:
                # glass-to-glass probe: every DNGD_JOURNEY_SAMPLE-th
                # frame's fragment is preceded by an fprobe the client
                # echoes back as {"type": "ack", "id": fid} — the
                # journey's client-side closure (obs/journey)
                if (kind == "frag" and len(item) > 3 and item[3]
                        and obsj.probe_due(item[3])):
                    # record the outstanding fid BEFORE the probe can
                    # race its own ack: only ids in this window may
                    # close journeys (resilience/ingress ack gating)
                    if probes is not None:
                        probes.add(item[3])
                    await ws.send_json({"type": "fprobe", "id": item[3]})
                await ws.send_bytes(data)
    except Exception:
        pass


def _teardown_peer(conn: dict, session) -> None:
    if conn.get("on_au") is not None and hasattr(session,
                                                 "remove_au_listener"):
        session.remove_au_listener(conn["on_au"])
        conn["on_au"] = None
    audio = conn.get("audio")
    if conn.get("on_audio") is not None and audio is not None:
        audio.remove_listener(conn["on_audio"])
        conn["on_audio"] = None
    if conn.get("peer") is not None:
        conn["peer"].close()
        conn["peer"] = None


async def _handle_offer(msg: dict, ws, session, conn: dict) -> None:
    """SDP offer -> first-party WebRTC media plane when the session can
    feed it, else the MSE-over-WS capability statement (the fallback the
    client already speaks)."""
    sdp_text = msg.get("sdp", "")
    codec_name = getattr(session, "codec_name", "")
    rtc_codec = ("H264" if codec_name.startswith("h264") else
                 "VP8" if codec_name.startswith("vp8") else None)
    can_rtc = (conn is not None and sdp_text and rtc_codec is not None
               and hasattr(session, "add_au_listener"))
    if not can_rtc:
        await ws.send_json({"type": "answer", "transport": "mse-ws"})
        return
    audio = conn.get("audio")
    rtc_audio = audio is not None and getattr(audio, "format", "") == "opus"
    peer = None
    try:
        from ..webrtc.peer import WebRtcPeer

        _teardown_peer(conn, session)        # renegotiation replaces peer
        peer = WebRtcPeer(clock=getattr(session, "clock", None),
                          video_codec=rtc_codec,
                          advertise_ip=conn["advertise_ip"],
                          with_audio=rtc_audio,
                          turn=conn.get("turn"))
        # RTCP journey closure: the peer maps RR extended-highest-seq
        # back to frame pts and closes through the session's book
        peer.journeys = getattr(session, "journeys", None)
        # the connection's abuse governor covers this peer's RTCP/SCTP/
        # DCEP ingest too, and stats-channel acks gate on the same
        # outstanding-probe window as /ws acks (resilience/ingress)
        peer.set_ingress_budget(conn.get("budget"))
        peer.ingress_probes = conn.get("probes")
        # data-channel input (if the offer carries m=application): same
        # binder as the stock-selkies shim, so both clients' channel
        # input exercises one path
        from .selkies_shim import attach_input_channels
        import asyncio
        attach_input_channels(peer, session, conn.get("injector"),
                              loop=asyncio.get_running_loop())
        # resumed connection (resilience/handoff): seed the predecessor
        # peer's wire continuity BEFORE the offer — the answer SDP must
        # advertise the same SSRCs the client was already decoding
        if conn.get("resume_wire"):
            peer.import_wire(conn["resume_wire"])
            conn["resume_wire"] = None       # single-shot
        answer_sdp = await peer.handle_offer(sdp_text)
        if conn.get("client_ip"):
            # cover the pre-trickle window: the client's checks will come
            # from (at least) the address its websocket came from
            await peer.add_remote_candidate_ip(conn["client_ip"])
    except Exception as e:
        from ..webrtc.sdp import SdpError
        if peer is not None:
            # release the socket AND the peer's per-ssrc metric series —
            # a leaked half-built peer would be scraped stale forever
            peer.close()
        if isinstance(e, SdpError):
            # hostile/corrupt offer rejected at the trust boundary: a
            # clean signaling error + violation score, not a stack
            # trace and not a silent mse-ws downgrade the client
            # would then negotiate against forever
            log.warning("offer rejected at trust boundary: %s (%s)",
                        e.reason, e)
            budget = conn.get("budget")
            if budget is not None:
                budget.violation(e.reason, weight=5.0)
            await ws.send_json({"type": "error", "reason": e.reason})
            return
        log.exception("webrtc offer failed; answering mse-ws")
        await ws.send_json({"type": "answer", "transport": "mse-ws"})
        return
    conn["peer"] = peer
    # this peer's wire state becomes migratable: if THIS process drains
    # next, its RTP/SRTP/SCTP frontier rides the snapshot
    if conn.get("handoff_attach") is not None:
        conn["handoff_attach"](peer.export_wire)

    def on_au(au, keyframe, pts):
        peer.send_video_au(au, pts)

    conn["on_au"] = on_au
    session.add_au_listener(on_au)
    if rtc_audio:
        def on_audio(pts, packet):
            peer.send_audio(packet, pts)

        conn["on_audio"] = on_audio
        audio.add_listener(on_audio)
    # first IDR right when SRTP comes up so video starts instantly
    if hasattr(session, "request_keyframe"):
        peer.on_ready = session.request_keyframe
    # PLI/FIR land on the session's rate-limited request_idr so a
    # client's keyframe storm dedupes against the degrade ladder's IDR
    # rung and the collect-failure resync (webrtc/feedback)
    from .session import keyframe_requester
    peer.on_keyframe_request = keyframe_requester(session)
    # media now rides SRTP; stop duplicating fMP4 frags to this client
    session.unsubscribe(conn["queue"])
    await ws.send_json({"type": "answer", "transport": "webrtc",
                        "sdp": answer_sdp})


async def _handle_client_msg(text: str, ws, session, injector: Injector,
                             loop=None, conn: Optional[dict] = None):
    """Control-plane messages: JSON signaling or compact input strings."""
    budget = conn.get("budget") if conn is not None else None
    if text.startswith("{"):
        if budget is not None and not budget.allow_nonmedia():
            # quarantined: control-plane JSON drops, and a peer that
            # keeps hammering THROUGH its cooldown climbs toward the
            # evict rung instead of parking at quarantine forever
            budget.violation("quarantine_ingest", weight=0.2)
            return
        if budget is not None and not budget.charge("signal"):
            # over the signaling rate: drop (already counted); raw
            # input below keeps its own parse hardening + bounded queue
            return
        try:
            msg = json.loads(text)
        except ValueError:
            if budget is not None:
                budget.violation("signal_bad_json")
            return
        if not isinstance(msg, dict):
            if budget is not None:
                budget.violation("signal_bad_json", weight=0.5)
            return
        mtype = msg.get("type")
        if mtype == "ping":
            await ws.send_json({"type": "pong", "t": msg.get("t")})
        elif mtype == "ack":
            # client ack of a sampled frame probe: closes the frame's
            # journey at SERVER receipt time (no clock sync needed; the
            # measured g2g honestly includes the ack's uplink).  Only
            # fids THIS connection was probed with may close — spoofed,
            # replayed or future ids would otherwise fabricate the g2g
            # p50 the SLO verdict admits against.
            if budget is not None and not budget.charge("ack"):
                return
            try:
                fid = int(msg.get("id", 0))
            except (TypeError, ValueError):
                if budget is not None:
                    budget.violation("ack_spoof", weight=0.5)
                return
            probes = conn.get("probes") if conn is not None else None
            if probes is not None and not probes.take(fid):
                if budget is not None:
                    budget.violation("ack_spoof", weight=0.5)
                return
            book = getattr(session, "journeys", None)
            if book is not None:
                book.close(fid, method="client")
        elif mtype == "offer":
            await _handle_offer(msg, ws, session, conn)
        elif mtype == "candidate":
            # ICE-lite: the peer address comes from checks; but when our
            # media is relayed, the TURN server drops a new address's
            # checks until a permission exists for it (RFC 5766 §9)
            cand = msg.get("candidate") or ""
            if isinstance(cand, dict):
                cand = cand.get("candidate", "") or ""
            peer = conn.get("peer") if conn is not None else None
            parts = cand.split() if isinstance(cand, str) else []
            if peer is not None and len(parts) >= 5:
                await peer.add_remote_candidate_ip(parts[4])
        elif mtype == "stats":
            data = session.stats_summary()
            if conn is not None and conn.get("peer") is not None:
                data["webrtc"] = conn["peer"].stats()
            await ws.send_json({"type": "stats", "data": data})
        return
    # A bound WebRTC peer serializes ALL input for this connection
    # through its per-peer worker (selkies_shim.attach_input_channels):
    # without it, events spanning the WS -> data-channel switchover
    # would be injected by two concurrent executor hops out of order.
    peer = conn.get("peer") if conn is not None else None
    enqueue = getattr(peer, "input_enqueue", None)
    if enqueue is not None:
        enqueue(text)
        return
    await handle_input_text(text, session, injector, loop)


async def handle_input_text(text: str, session,
                            injector: Optional[Injector],
                            loop=None) -> None:
    """One compact CSV input message -> injection + codec control.

    The SINGLE input path: the /ws handler and the SCTP data-channel
    binders (selkies_shim.attach_input_channels) both land here, so a
    keystroke arriving over either transport reaches the X backend
    through identical parsing, hardening and executor offload."""
    if injector is None:
        # Session without an input path (e.g. a synthetic batch session):
        # still honor the codec-control messages below.
        from .input import parse_message
        event = parse_message(text)
    # Injection backends may block (xdotool subprocess): keep them off the
    # event loop so one hung X call can't stall media delivery to everyone.
    elif loop is not None:
        event = await loop.run_in_executor(None, injector.handle_message,
                                           text)
    else:
        event = injector.handle_message(text)
    if event is not None and event.get("type") == "keyframe":
        # session-level request (wakes an idle encode loop) when offered
        if hasattr(session, "request_keyframe"):
            session.request_keyframe()
        else:
            session.encoder.request_keyframe()
    elif event is not None and event.get("type") == "resize":
        ok = (session.request_resize(event["width"], event["height"])
              if hasattr(session, "request_resize") else False)
        if not ok:
            log.info("resize to %dx%d rejected (WEBRTC_ENABLE_RESIZE off "
                     "or source not resizable)",
                     event["width"], event["height"])


def _ssl_context(cfg: Config) -> Optional[ssl.SSLContext]:
    if not cfg.enable_https_web:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cfg.https_web_cert, cfg.https_web_key)
    return ctx


async def serve(cfg: Config, session=None, injector=None,
                supervisor=None, joystick=None, audio=None,
                manager=None) -> web.AppRunner:
    runner = web.AppRunner(make_app(cfg, session, injector, supervisor,
                                    joystick, audio, manager))
    await runner.setup()
    site = web.TCPSite(runner, cfg.listen_addr, cfg.listen_port,
                       ssl_context=_ssl_context(cfg))
    await site.start()
    return runner


def bound_port(runner: web.AppRunner) -> int:
    for site in runner.sites:
        server = site._server  # noqa: SLF001
        if server and server.sockets:
            return server.sockets[0].getsockname()[1]
    raise RuntimeError("server not bound")
