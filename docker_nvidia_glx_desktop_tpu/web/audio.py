"""Audio path: PulseAudio capture -> Opus -> WebSocket -> WebAudio.

The reference runs system-wide PulseAudio (supervisord.conf:22-32) and
selkies builds an opus WebRTC track from ``pulsesrc`` (SURVEY.md §3.2).
First-party equivalent without GStreamer: capture PCM from the Pulse server
with ``parec`` (ships with the pulseaudio package the image installs),
encode 20 ms frames with libopus (``native/opus.py`` ctypes binding,
~128 kbit/s vs ~1.5 Mbit/s raw), and stream them over a dedicated
``/audio`` WebSocket.  Every packet is prefixed with a 4-byte big-endian
timestamp on the shared 90 kHz :class:`..web.clock.MediaClock` — the A/V
sync contract the client schedules WebAudio against.  Raw s16le remains
the fallback when libopus is unavailable (``AUDIO_CODEC=pcm`` forces it).

Sources:
- :class:`ParecSource` — real capture from ``$PULSE_SERVER`` (container).
- :class:`ToneSource`  — synthetic sine (tests; also the audible "is audio
  working at all" probe, VERDICT round-1 'done' bar: a test client
  receives a tone).
"""

from __future__ import annotations

import asyncio
import logging
import math
import shutil
import struct
import subprocess
import threading
import time
from typing import List, Optional

log = logging.getLogger(__name__)

__all__ = ["AudioSession", "ParecSource", "ToneSource", "make_audio_source"]

RATE = 48_000
CHANNELS = 2
CHUNK_FRAMES = 960            # 20 ms at 48 kHz
CHUNK_BYTES = CHUNK_FRAMES * CHANNELS * 2


class ParecSource:
    """PCM from the PulseAudio native protocol via parec."""

    def __init__(self, pulse_server: Optional[str] = None):
        if shutil.which("parec") is None:
            raise RuntimeError("parec not installed")
        cmd = ["parec", "--format=s16le", f"--rate={RATE}",
               f"--channels={CHANNELS}", "--latency-msec=20"]
        env = None
        if pulse_server:
            import os
            env = dict(os.environ, PULSE_SERVER=pulse_server)
        self._proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, env=env)

    def read_chunk(self) -> bytes:
        data = self._proc.stdout.read(CHUNK_BYTES)
        if not data:
            raise EOFError("parec stream ended")
        return data

    def close(self) -> None:
        self._proc.terminate()


class ToneSource:
    """Deterministic sine tone at ``freq`` Hz, real-time paced."""

    def __init__(self, freq: float = 440.0, pace: bool = True):
        self.freq = freq
        self._pace = pace
        self._phase = 0
        self._t0 = time.monotonic()
        self._sent_frames = 0

    def read_chunk(self) -> bytes:
        if self._pace:
            due = self._t0 + self._sent_frames / RATE
            delay = due - time.monotonic()
            if delay > 0:
                time.sleep(delay)
        out = bytearray()
        w = 2 * math.pi * self.freq / RATE
        for i in range(CHUNK_FRAMES):
            v = int(12_000 * math.sin(w * (self._phase + i)))
            out += struct.pack("<hh", v, v)
        self._phase += CHUNK_FRAMES
        self._sent_frames += CHUNK_FRAMES
        return bytes(out)

    def close(self) -> None:
        pass


def make_audio_source(pulse_server: Optional[str] = None):
    """Real capture when pulse is reachable, else None (no audio track —
    parity with the noVNC path's documented no-audio trade)."""
    try:
        return ParecSource(pulse_server)
    except Exception:
        return None


class AudioSession:
    """Capture thread fanning encoded chunks out to subscriber queues.

    ``source_factory`` (optional) rebuilds the source after a capture error
    — parec dies whenever PulseAudio restarts (supervisord restarts it,
    reference supervisord.conf:30), so the session must reconnect rather
    than go permanently silent while clients are still told audio exists.

    Wire format (binary WS message): ``u32be pts90k || payload`` where
    payload is one Opus packet (format "opus") or one s16le PCM chunk
    (format "s16le"); the header message announces which.
    """

    def __init__(self, source, loop=None, source_factory=None,
                 retry_s: float = 2.0, clock=None, codec: str = "opus",
                 bitrate: int = 128_000):
        from .clock import MediaClock

        self.source = source
        self.loop = loop
        self.source_factory = source_factory
        self.retry_s = retry_s
        self.clock = clock if clock is not None else MediaClock()
        self._subscribers: List[asyncio.Queue] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._enc = None
        fmt = "s16le"
        if codec == "opus":
            try:
                from ..native.opus import OpusEncoder
                self._enc = OpusEncoder(rate=RATE, channels=CHANNELS,
                                        bitrate=bitrate)
                fmt = "opus"
            except Exception:
                log.warning("libopus unavailable; audio falls back to "
                            "raw s16le PCM")
        self.header = {"type": "audio", "format": fmt, "rate": RATE,
                       "channels": CHANNELS, "chunk_frames": CHUNK_FRAMES,
                       "ts_rate": self.clock.RATE}
        # packet taps (WebRTC peers): fn(pts90k, payload), capture thread
        self._listeners: List = []

    @property
    def format(self) -> str:
        return self.header["format"]

    def add_listener(self, fn) -> None:
        self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        if fn in self._listeners:
            self._listeners.remove(fn)

    def subscribe(self, maxsize: int = 50) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)
        self._subscribers.append(q)
        return q

    def unsubscribe(self, q: asyncio.Queue) -> None:
        if q in self._subscribers:
            self._subscribers.remove(q)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="audio-session")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread_dead = True
        if self._thread is not None:
            self._thread.join(timeout=5)
            thread_dead = not self._thread.is_alive()
            self._thread = None
        if self.source is not None:
            try:
                self.source.close()
            except Exception:
                pass
            self.source = None
        # Destroying the native encoder while the capture thread might
        # still call opus_encode would be a use-after-free (segfault, not
        # an exception) — only close it once the thread is confirmed dead;
        # otherwise leak it and let interpreter teardown reclaim.
        if self._enc is not None and thread_dead:
            try:
                self._enc.close()
            except Exception:
                pass
            self._enc = None

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                chunk = self.source.read_chunk()
            except Exception:
                if self.source_factory is None:
                    log.exception("audio capture ended (no restart factory)")
                    return
                log.warning("audio capture error; reconnecting in %.1fs",
                            self.retry_s)
                try:
                    self.source.close()
                except Exception:
                    pass
                if self._stop.wait(self.retry_s):
                    return
                try:
                    self.source = self.source_factory()
                except Exception:
                    continue
                if self.source is None:
                    continue
                continue
            pts = self.clock.now90k()
            enc = self._enc
            if enc is not None:
                try:
                    chunk = enc.encode(chunk)
                except Exception:
                    log.exception("opus encode failed; dropping chunk")
                    continue
            for fn in list(self._listeners):
                try:
                    fn(pts, chunk)
                except Exception:
                    log.exception("audio listener failed")
            msg = struct.pack(">I", pts) + chunk
            if self.loop is not None:
                self.loop.call_soon_threadsafe(self._publish, msg)
            else:
                self._publish(msg)

    def _publish(self, chunk: bytes) -> None:
        for q in list(self._subscribers):
            while True:
                try:
                    q.put_nowait(chunk)
                    break
                except asyncio.QueueFull:
                    try:
                        q.get_nowait()       # latest-wins, like video
                    except asyncio.QueueEmpty:
                        break
