"""CLI entry for the streaming server (the ``streamer`` program in the boot
plan — the selkies-gstreamer-entrypoint.sh:43-47 role): capture the
configured display (synthetic source when no X), encode on TPU, serve the
web client + websocket on ``LISTEN_PORT``."""

from __future__ import annotations

import asyncio
import logging

from ..rfb.source import make_source
from ..utils.config import from_env
from .input import make_injector
from .server import serve
from .session import StreamSession

log = logging.getLogger(__name__)


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    cfg = from_env()
    # Persistent XLA compile cache: restarts and the qp-ladder prewarm
    # skip every compile a previous process already did.
    from ..utils.jaxcache import setup_compile_cache
    setup_compile_cache()

    async def run():
        from .clock import MediaClock

        loop = asyncio.get_running_loop()
        clock = MediaClock()        # ONE A/V timeline for every transport
        manager = None
        session = None
        if cfg.tpu_sessions > 1:
            # BASELINE config 5: N sessions, one batched device program.
            # Session 0 captures the real display when one exists; the
            # rest are synthetic until multi-display provisioning lands.
            # Only session 0 gets a real input path (cross-session input
            # isolation).
            from .multisession import BucketedStreamManager
            sizes = cfg.session_sizes()
            sources = [make_source(cfg.display if i == 0 else None,
                                   sizes[i][0], sizes[i][1])
                       for i in range(cfg.tpu_sessions)]
            injectors = [make_injector(cfg.display) if i == 0 else None
                         for i in range(cfg.tpu_sessions)]
            manager = BucketedStreamManager(cfg, sources, loop=loop,
                                            injectors=injectors)
            manager.start()
            injector = None      # per-hub injectors own all input routing
        else:
            source = make_source(cfg.display, cfg.sizew, cfg.sizeh)
            session = StreamSession(cfg, source, loop=loop, clock=clock)
            session.start()
            injector = make_injector(cfg.display)
        from .joystick import JoystickHub
        joystick = JoystickHub()
        try:
            await joystick.start()
        except OSError:
            logging.exception("joystick hub disabled")
            joystick = None
        from .audio import AudioSession, make_audio_source
        audio_src = make_audio_source(cfg.pulse_server)
        audio = None
        if audio_src is not None:
            audio = AudioSession(
                audio_src, loop=loop,
                source_factory=lambda: make_audio_source(cfg.pulse_server),
                codec=cfg.audio_codec, bitrate=cfg.audio_bitrate,
                clock=clock)
            audio.start()
        else:
            logging.info("no PulseAudio capture; audio track disabled")
        runner = await serve(cfg, session, injector, joystick=joystick,
                             audio=audio, manager=manager)
        logging.info("streaming server on %s:%d (%d session(s), %dx%d)",
                     cfg.listen_addr, cfg.listen_port,
                     cfg.tpu_sessions if manager else 1,
                     cfg.sizew, cfg.sizeh)
        # Startup memory picture (VERDICT r5 weak #4): peak host RSS +
        # compile-cache hit/miss, logged once and live on /metrics as
        # process_peak_rss_bytes / jax_compile_cache_*_total.
        from ..obs.procstats import log_startup
        log_startup()

        # Graceful drain on SIGTERM (k8s pod deletion; see the preStop
        # hook in deploy/xgl-tpu.yml).  With DNGD_HANDOFF_DIR/_SOCK set
        # this MIGRATES: snapshot sessions + wire continuity for the
        # successor, hand each client a resume token, then exit once
        # the snapshot is safely spooled/streamed.  Without it, legacy
        # drain: stop admitting, tell clients to pre-connect elsewhere,
        # flush DRAIN_GRACE_S, exit — either way well inside
        # terminationGracePeriodSeconds, so SIGKILL never lands.
        stop = asyncio.Event()

        def _drain_then_stop(signame: str) -> None:
            from .server import _spawn_bg

            migrate = runner.app.get("handoff_migrate")
            handoff = runner.app.get("handoff")
            if migrate is not None and handoff is not None \
                    and handoff.enabled:
                async def _migrate_then_stop():
                    try:
                        await migrate(signame)
                        # short flush: the migrate message must reach
                        # every client socket before the process dies
                        await asyncio.sleep(
                            min(cfg.drain_grace_s, 2.0))
                    except Exception:
                        log.exception("handoff migrate failed; "
                                      "exiting after the grace window")
                        await asyncio.sleep(cfg.drain_grace_s)
                    stop.set()

                _spawn_bg(_migrate_then_stop())
                return
            begin = runner.app.get("begin_drain")
            if begin is not None:
                begin(signame)

            async def _grace():
                await asyncio.sleep(cfg.drain_grace_s)
                stop.set()

            # keep a strong ref: a bare ensure_future is only weakly
            # held by the loop and GC could collect the grace timer —
            # the pod would then drain forever instead of exiting
            # (analysis finding async-task-leak)
            _spawn_bg(_grace())

        # SIGTERM only: Ctrl-C (SIGINT) keeps its immediate
        # KeyboardInterrupt teardown for local iteration — the drain
        # grace is for orchestrated shutdowns, not developer loops
        import signal
        try:
            loop.add_signal_handler(
                signal.SIGTERM, _drain_then_stop, "SIGTERM")
        except (NotImplementedError, RuntimeError):
            pass                           # non-unix event loop
        try:
            await stop.wait()
        finally:
            # full close (not bare stop): releases the per-session
            # observability state so a supervised restart in the same
            # process never accumulates registry leftovers
            if session is not None:
                session.close()
            if manager is not None:
                manager.close()
            await runner.cleanup()

    asyncio.run(run())


if __name__ == "__main__":
    main()
