"""CLI entry for the streaming server (the ``streamer`` program in the boot
plan — the selkies-gstreamer-entrypoint.sh:43-47 role): capture the
configured display (synthetic source when no X), encode on TPU, serve the
web client + websocket on ``LISTEN_PORT``."""

from __future__ import annotations

import asyncio
import logging

from ..rfb.source import make_source
from ..utils.config import from_env
from .input import make_injector
from .server import serve
from .session import StreamSession


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    cfg = from_env()

    async def run():
        loop = asyncio.get_running_loop()
        source = make_source(cfg.display, cfg.sizew, cfg.sizeh)
        session = StreamSession(cfg, source, loop=loop)
        injector = make_injector(cfg.display)
        session.start()
        from .joystick import JoystickHub
        joystick = JoystickHub()
        try:
            await joystick.start()
        except OSError:
            logging.exception("joystick hub disabled")
            joystick = None
        from .audio import AudioSession, make_audio_source
        audio_src = make_audio_source(cfg.pulse_server)
        audio = None
        if audio_src is not None:
            audio = AudioSession(
                audio_src, loop=loop,
                source_factory=lambda: make_audio_source(cfg.pulse_server))
            audio.start()
        else:
            logging.info("no PulseAudio capture; audio track disabled")
        runner = await serve(cfg, session, injector, joystick=joystick,
                             audio=audio)
        logging.info("streaming server on %s:%d (%s, %dx%d)",
                     cfg.listen_addr, cfg.listen_port, session.codec_name,
                     source.width, source.height)
        try:
            await asyncio.Event().wait()
        finally:
            session.stop()
            await runner.cleanup()

    asyncio.run(run())


if __name__ == "__main__":
    main()
