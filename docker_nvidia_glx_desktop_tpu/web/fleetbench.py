"""Fleet churn bench: many joining/leaving loopback clients, one mesh.

``bench.py --fleet`` drives the REAL multi-tenant serving path — N
SessionHubs batch-encoded by one BatchStreamManager over a simulated
v5e-8 (forced host-platform devices on CPU), fronted by the fleet
admission scheduler (fleet/) — with a churning population of loopback
websocket clients, each behaving like the first-party client: join,
stream for a while, leave, and on a ``busy`` rejection back off by the
server's ``retry_after_s`` with the resilience/policy full-jitter
formula before retrying.

Mid-churn the two chaos scenarios the fleet must absorb are injected:

- ``mesh_chip_lost`` — capacity shrinks under live load; the manager's
  elastic rebuild migrates every session's lineage (host-side GOP
  checkpoint + recovery IDR), the scheduler re-reads the chip pool and
  sheds newest/lowest-tier first ONLY if degradation couldn't absorb
  the loss;
- ``ws_send_stall`` — wedged clients trip slow-subscriber eviction
  while their bucket-mates keep streaming.

The report carries the acceptance numbers: sessions/chip at SLO, p99
join latency, rejection rate, and the zero-crash invariants (every join
attempt resolved admitted/queued/rejected — no silent hangs; server and
encode loop alive at the end).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Optional

from ..obs.budget import LEDGER
from ..resilience import faults as rfaults
from ..resilience.policy import RetryPolicy
from ..utils.timing import percentile
from .loopback import serving_budget_config

log = logging.getLogger(__name__)

__all__ = ["run_fleet"]


class _ClientStats:
    __slots__ = ("attempts", "admitted", "busy", "busy_reasons",
                 "retry_after_ok", "hangs", "errors", "frags",
                 "evicted", "shed", "resumed_after_rebuild",
                 "join_wait_ms")

    def __init__(self):
        self.attempts = 0
        self.admitted = 0
        self.busy = 0
        self.busy_reasons: dict = {}
        self.retry_after_ok = True      # every busy carried retry_after_s
        self.hangs = 0
        self.errors = 0
        self.frags = 0
        self.evicted = 0
        self.shed = 0
        self.resumed_after_rebuild = 0
        self.join_wait_ms: list = []


async def _fleet_client(idx: int, port: int, st: _ClientStats,
                        stop_at: float, hold_s: float, rng,
                        answer_timeout_s: float,
                        rebuild_t: list) -> None:
    """One churning client: the first-party join/stream/leave loop with
    the busy/retry contract (jittered reconnect off ``retry_after_s``)."""
    import aiohttp

    url = f"http://127.0.0.1:{port}/ws"
    attempt = 0
    async with aiohttp.ClientSession() as http:
        while time.monotonic() < stop_at:
            st.attempts += 1
            t0 = time.perf_counter()
            try:
                async with http.ws_connect(url, max_msg_size=0) as ws:
                    msg = await ws.receive(timeout=answer_timeout_s)
                    if msg.type != aiohttp.WSMsgType.TEXT:
                        st.hangs += 1          # closed without an answer
                        continue
                    first = json.loads(msg.data)
                    if first.get("type") == "busy":
                        st.busy += 1
                        reason = first.get("reason", "?")
                        st.busy_reasons[reason] = \
                            st.busy_reasons.get(reason, 0) + 1
                        retry = first.get("retry_after_s")
                        if not isinstance(retry, (int, float)) \
                                or retry <= 0:
                            st.retry_after_ok = False
                            retry = 1.0
                        # the busy contract: back off by the server's
                        # hint with FULL JITTER (resilience/policy) so
                        # rejected joiners spread, never herd
                        policy = RetryPolicy(initial=float(retry),
                                             cap=float(retry) * 8,
                                             floor=float(retry) * 0.5)
                        attempt += 1
                        await asyncio.sleep(min(
                            policy.delay(attempt - 1, rng=rng.random),
                            max(stop_at - time.monotonic(), 0.0)))
                        continue
                    if first.get("type") != "hello":
                        st.errors += 1          # draining / error
                        continue
                    attempt = 0
                    st.admitted += 1
                    st.join_wait_ms.append(
                        (time.perf_counter() - t0) * 1e3)
                    hold_deadline = time.monotonic() + hold_s
                    connected_before_rebuild = not rebuild_t
                    while time.monotonic() < min(hold_deadline, stop_at):
                        left = min(hold_deadline, stop_at) \
                            - time.monotonic()
                        try:
                            m = await ws.receive(
                                timeout=max(left, 0.05))
                        except asyncio.TimeoutError:
                            break
                        if m.type == aiohttp.WSMsgType.BINARY:
                            st.frags += 1
                            if connected_before_rebuild and rebuild_t \
                                    and time.perf_counter() > rebuild_t[0]:
                                # same socket, media after the elastic
                                # rebuild: the migrated lineage resumed
                                st.resumed_after_rebuild += 1
                                connected_before_rebuild = False
                        elif m.type == aiohttp.WSMsgType.TEXT:
                            if '"evicted"' in m.data:
                                st.evicted += 1
                                break
                            if '"busy"' in m.data:   # shed mid-stream
                                st.shed += 1
                                break
                        else:
                            break
            except asyncio.TimeoutError:
                st.hangs += 1
            except Exception:
                st.errors += 1
            # think time before the next join
            await asyncio.sleep(0.2 + 0.6 * rng.random())


async def run_fleet(quick: bool = False,
                    n_clients: Optional[int] = None,
                    churn_s: Optional[float] = None,
                    timeout_s: float = 600.0,
                    seed: int = 7) -> dict:
    """Run the churn bench; returns the ``fleet`` report block."""
    import random

    import jax

    from .multisession import BatchStreamManager
    from .server import bound_port, serve
    from ..rfb.source import SyntheticSource

    ndev = len(jax.devices())
    n_hubs = min(ndev, 8)
    if quick:
        w, h, fps = 128, 96, 30
        n_clients = n_clients or max(2 * n_hubs, 8)
        churn_s = churn_s or 30.0
        per_chip, queue_depth, queue_timeout = 1, 3, 3.0
    else:
        # the acceptance geometry: 8x 1080p on the simulated v5e-8,
        # capacity from the ledger-fed model (not pinned)
        w, h, fps = 1920, 1080, 60
        n_clients = n_clients or 120
        churn_s = churn_s or 120.0
        per_chip, queue_depth, queue_timeout = 0, 8, 6.0
    cfg = serving_budget_config(w, h, fps, extra={
        "TPU_SESSIONS": str(n_hubs),
        "TPU_MESH": str(n_hubs),
        "ENCODER_GOP": "30",
        "WEBRTC_ENABLE_RESIZE": "true",
        "FLEET_ENABLE": "true",
        "FLEET_SESSIONS_PER_CHIP": str(per_chip),
        "FLEET_QUEUE_DEPTH": str(queue_depth),
        "FLEET_QUEUE_TIMEOUT_S": str(queue_timeout),
        "FLEET_RETRY_AFTER_S": "1.0" if quick else "2.0",
    })
    rfaults.disarm_all()
    LEDGER.clear()
    # batched ticks feed tracer('batch') -> the ledger; with the serving
    # context set, the capacity model measures us/MB from live data and
    # the SLO rungs gate the run
    LEDGER.set_context(w, h, fps, sessions=n_hubs)
    loop = asyncio.get_running_loop()
    sources = [SyntheticSource(w, h, fps=float(fps))
               for _ in range(n_hubs)]
    mgr = BatchStreamManager(cfg, sources, loop=loop)
    mgr.start()
    runner = await serve(cfg, manager=mgr)
    port = bound_port(runner)
    sched = runner.app["fleet"]
    assert sched is not None, "FLEET_ENABLE did not build a scheduler"
    rng = random.Random(seed)
    stats = [_ClientStats() for _ in range(n_clients)]
    rebuild_t: list = []          # [t_perf] set when the chip drops
    samples: list = []            # (active, queued) trajectory
    t_start = time.perf_counter()
    report: dict = {
        "mode": "fleet-loopback", "quick": quick,
        "geometry": f"{w}x{h}@{fps}", "hubs": n_hubs,
        "chips_start": n_hubs, "clients": n_clients,
        "churn_s": churn_s, "seed": seed,
        "capacity_start": sched.capacity,
    }
    try:
        # warm up: one in-process subscriber per hub waits for its first
        # keyframe, then hub 0 for a SECOND one — a full GOP of P ticks,
        # so the P-step compile lands before churn.  Without it the
        # encode loop stalls inside XLA across the fault-consumption
        # window and the mid-churn injections look like they never
        # fired (the same trap web/chaos.py documents).
        warm_qs = [mgr.session(i).subscribe() for i in range(n_hubs)]
        deadline = time.monotonic() + timeout_s * 0.5

        async def next_keyframe(q) -> bool:
            while time.monotonic() < deadline:
                try:
                    item = await asyncio.wait_for(q.get(), 1.0)
                except asyncio.TimeoutError:
                    continue
                if item[0] == "frag" and len(item) > 2 and item[2]:
                    return True
            return False

        for q in warm_qs:
            if not await next_keyframe(q):
                raise RuntimeError("fleet bench: no first keyframe "
                                   "within warmup budget")
        if not await next_keyframe(warm_qs[0]):
            raise RuntimeError("fleet bench: no second GOP before churn "
                               "(P-step compile did not finish)")
        for i, q in enumerate(warm_qs):
            mgr.session(i).unsubscribe(q)

        stop_at = time.monotonic() + churn_s
        answer_timeout = queue_timeout + 15.0   # queue wait + margin
        hold = (1.0, 3.0) if quick else (2.0, 6.0)
        clients = [asyncio.ensure_future(_fleet_client(
            i, port, stats[i], stop_at,
            hold[0] + (hold[1] - hold[0]) * rng.random(), rng,
            answer_timeout, rebuild_t)) for i in range(n_clients)]

        async def chaos():
            # chip loss at 40% of the window, stalled clients at 60%
            await asyncio.sleep(churn_s * 0.4)
            rebuilds_before = mgr._rebuilds
            rfaults.arm("mesh_chip_lost", count=1)
            t0 = time.monotonic()
            while (rfaults.armed_count("mesh_chip_lost")
                   and time.monotonic() - t0 < 30.0):
                await asyncio.sleep(0.1)
            report["mesh_chip_lost_fired"] = \
                1 - rfaults.armed_count("mesh_chip_lost")
            rfaults.disarm("mesh_chip_lost")
            # stamp the rebuild so clients classify post-rebuild media
            t0 = time.monotonic()
            while (mgr._rebuilds == rebuilds_before
                   and time.monotonic() - t0 < 30.0):
                await asyncio.sleep(0.1)
            rebuild_t.append(time.perf_counter())
            await asyncio.sleep(churn_s * 0.2)
            from .session import SubscriberSet
            stalls = SubscriberSet.SLOW_EVICT_STREAK + 40
            rfaults.arm("ws_send_stall", count=stalls, delay_ms=3000.0)
            await asyncio.sleep(min(15.0, churn_s * 0.2))
            report["ws_send_stall_fired"] = \
                stalls - rfaults.armed_count("ws_send_stall")
            rfaults.disarm("ws_send_stall")

        async def sampler():
            while time.monotonic() < stop_at:
                samples.append((sched.active, sched.queued,
                                sched.backpressure_level,
                                max(1, sched.n_chips)))
                await asyncio.sleep(0.2)

        chaos_task = asyncio.ensure_future(chaos())
        sample_task = asyncio.ensure_future(sampler())
        try:
            await asyncio.wait_for(
                asyncio.gather(*clients, return_exceptions=True),
                timeout=timeout_s)
        finally:
            for c in clients:
                c.cancel()
            sample_task.cancel()
        await asyncio.wait_for(chaos_task, timeout=60.0)
    finally:
        rfaults.disarm_all()
        alive = mgr._thread is not None and mgr._thread.is_alive()
        mgr_stats = mgr.stats_summary()
        chips_end = mgr.surviving_chips()
        snap = sched.snapshot()
        # before close(): manager teardown clears the ledger context the
        # rung evaluation needs
        budget_block = LEDGER.snapshot()
        await runner.cleanup()
        mgr.close()

    # -- aggregate ------------------------------------------------------
    attempts = sum(s.attempts for s in stats)
    admitted = sum(s.admitted for s in stats)
    busy = sum(s.busy for s in stats)
    hangs = sum(s.hangs for s in stats)
    errors = sum(s.errors for s in stats)
    waits = sorted(ms for s in stats for ms in s.join_wait_ms)
    busy_reasons: dict = {}
    for s in stats:
        for k, v in s.busy_reasons.items():
            busy_reasons[k] = busy_reasons.get(k, 0) + v
    peak_active = max((a for a, _, _, _ in samples), default=0)
    max_queue = max((q for _, q, _, _ in samples), default=0)
    max_bp = max((b for _, _, b, _ in samples), default=0)
    # density the fleet actually SERVED: active and chip count sampled
    # together — peak_active/chips_end would credit the pre-chip-loss
    # peak to the post-loss pool
    peak_per_chip = max((a / c for a, _, _, c in samples), default=0.0)
    frame_budget_ms = 1000.0 / max(fps, 1)
    # server-side SLO: the batched tick's encode time per session vs the
    # frame budget (hub FrameStats feed it), plus the ledger rung verdict
    enc_p50 = percentile(sorted(
        sess.get("encode_ms_p50", 0.0)
        for sess in mgr_stats["sessions"]), 50)
    active_rung = next((r for r in budget_block["rungs"].values()
                        if r["active"]), None)
    report.update({
        "chips_end": chips_end,
        "capacity_end": snap["capacity"],
        "wall_s": round(time.perf_counter() - t_start, 2),
        "joins": {
            "attempts": attempts, "admitted": admitted,
            "busy_rejected": busy, "busy_reasons": busy_reasons,
            "hangs": hangs, "errors": errors,
            "all_classified": attempts == admitted + busy + hangs
            + errors,
        },
        "join_wait_ms": {
            "p50": round(percentile(waits, 50), 1),
            "p99": round(percentile(waits, 99), 1),
            "n": len(waits),
        },
        "rejection_rate": round(busy / max(attempts, 1), 4),
        "retry_after_always_present": all(s.retry_after_ok
                                          for s in stats),
        "peak_active": peak_active,
        "max_queue_depth": max_queue,
        "sessions_per_chip": round(peak_per_chip, 2),
        "slo": {
            "frame_budget_ms": round(frame_budget_ms, 2),
            "session_encode_ms_p50": round(enc_p50, 2),
            "within_budget": enc_p50 <= frame_budget_ms,
            "rung": active_rung and {
                "ok": active_rung["ok"],
                "p50_ms": active_rung["p50_ms"],
                "budget_ms": active_rung["budget_ms"]},
        },
        "mesh": {
            "rebuilds": mgr_stats["mesh_rebuilds"],
            "dead_chips": mgr_stats["dead_chips"],
            "degrade_level": mgr_stats["degrade_level"],
            "shape": mgr_stats["mesh"],
            "geometry_end": mgr_stats["geometry"],
        },
        "shed": {"evicted": snap["sheds"],
                 "migrated": snap["migrations"],
                 "clients_shed_midstream": sum(s.shed for s in stats),
                 "clients_evicted_slow": sum(s.evicted for s in stats)},
        "backpressure_max_level": max(max_bp,
                                      snap["backpressure_level"]),
        "resumed_across_rebuild": sum(s.resumed_after_rebuild
                                      for s in stats),
        "frags_delivered": sum(s.frags for s in stats),
        "zero_crash": bool(alive),
        "fleet": snap,
    })
    report["ok"] = bool(
        alive and hangs == 0 and errors == 0 and admitted > 0
        and report["joins"]["all_classified"]
        and report["retry_after_always_present"]
        and report.get("mesh_chip_lost_fired", 0) == 1
        and report.get("ws_send_stall_fired", 0) >= 1
        and report["mesh"]["rebuilds"] >= 1
        and report["frags_delivered"] > 0)
    return report
