"""Shared media clock for A/V synchronization.

The reference's A/V sync is implicit in GStreamer's running-time model:
pulsesrc and ximagesrc stamp buffers against one pipeline clock and
webrtcbin maps that to RTP/RTCP (SURVEY.md §3.2).  The first-party
equivalent is one wall clock per process, read in the conventional 90 kHz
media timescale (``web/mp4.py`` TIMESCALE): the audio session stamps every
packet with it, the WebRTC RTCP sender reports map it to NTP time, and
the video path records capture times against it — so every transport
shares one timeline.
"""

from __future__ import annotations

import time

__all__ = ["MediaClock"]


class MediaClock:
    """Monotonic 90 kHz timeline anchored at construction."""

    RATE = 90_000

    def __init__(self):
        self.epoch = time.monotonic()

    def now90k(self) -> int:
        """Current media time in 90 kHz ticks (wraps like RTP at 2^32)."""
        return int((time.monotonic() - self.epoch) * self.RATE) & 0xFFFFFFFF

    def now90k_unwrapped(self) -> int:
        """Monotonic 90 kHz ticks WITHOUT the RTP 2^32 wrap — for
        consumers that need a non-wrapping timeline (the WebM cluster
        timestamps: a wrap after ~13 h would jump the MSE timeline back
        to zero and stall playback)."""
        return int((time.monotonic() - self.epoch) * self.RATE)

    def to_seconds(self, ts90k: int) -> float:
        return ts90k / self.RATE
