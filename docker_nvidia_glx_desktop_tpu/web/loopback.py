"""Loopback end-to-end serving bench: the full path, measured locally.

VERDICT r5 weak #1 / next-round item 6: device-only numbers prove the
kernels (devloop), the tunnel serving numbers prove nothing about the
host stages because a ~135 ms link RTT swamps them.  This module drives
the REAL serving path end to end on one box — synthetic X source ->
StreamSession (pipelined encode) -> muxer -> aiohttp server -> a local
WebSocket media sink — and reads the serving-budget ledger (obs/budget)
the session fed while it ran.  The result is the ``serving_budget``
block BENCH emits: per-stage p50s with the host<->device link cost
separated out (devloop round-trip probe), and the BASELINE ladder SLO
verdicts with per-stage attribution.

Everything uses the production code paths: the same SubscriberSet
fan-out, the same Mp4Muxer/WebM fragmenting, the same /ws handler a
browser speaks.  Only the pixels (SyntheticSource) and the sink (a
loopback aiohttp client) are synthetic.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

from ..obs.budget import LEDGER
from ..rfb.source import SyntheticSource
from ..utils.config import Config, from_env
from ..utils.timing import percentile

__all__ = ["run_serving_budget", "serving_budget_config"]


def serving_budget_config(width: int, height: int, fps: int = 60,
                          extra: Optional[dict] = None) -> Config:
    """Bench config: auth off (the sink is loopback), ephemeral port,
    CQP (no rate-control qp ladder to prewarm), short GOP so both frame
    types are measured."""
    env = {
        "SIZEW": str(width), "SIZEH": str(height), "REFRESH": str(fps),
        "ENABLE_BASIC_AUTH": "false",
        "LISTEN_ADDR": "127.0.0.1", "LISTEN_PORT": "0",
        "ENCODER_PREWARM": "false",
        "ENCODER_BITRATE_KBPS": "0",
        "ENCODER_GOP": "30",
        # the bench MEASURES the budget; the degradation ladder reacting
        # to it mid-run would distort the very numbers being taken
        "DEGRADE_ENABLE": "false",
    }
    env.update(extra or {})
    return from_env(env)


async def _drain_ws(ws, n_frames: int, timeout_s: float,
                    has_init: bool = True) -> dict:
    """Consume the media websocket like a browser: hello JSON, init
    segment (fMP4/WebM codecs only), then media fragments.  ``fprobe``
    control messages are echoed back as acks exactly like the web
    client does, so the server's glass-to-glass journeys close through
    the REAL loopback round trip.  Returns sink-side arrival stats —
    the only numbers the server-side ledger cannot know."""
    import json

    import aiohttp

    frags = 0
    nbytes = 0
    acks = 0
    skip = 1 if has_init else 0       # init segment carries no samples
    arrivals = []
    deadline = time.perf_counter() + timeout_s
    while frags < n_frames:
        left = deadline - time.perf_counter()
        if left <= 0:
            break
        try:
            msg = await ws.receive(timeout=left)
        except asyncio.TimeoutError:
            break
        if msg.type == aiohttp.WSMsgType.BINARY:
            arrivals.append(time.perf_counter())
            if len(arrivals) > skip:
                frags += 1
                nbytes += len(msg.data)
        elif msg.type == aiohttp.WSMsgType.TEXT:
            try:
                ctrl = json.loads(msg.data)
            except ValueError:
                continue
            if ctrl.get("type") == "fprobe":
                await ws.send_json({"type": "ack", "id": ctrl["id"],
                                    "recv_ts": time.perf_counter()})
                acks += 1
        elif msg.type in (aiohttp.WSMsgType.CLOSED,
                          aiohttp.WSMsgType.ERROR):
            break
    media = arrivals[skip:]
    intervals = sorted((b - a) * 1e3 for a, b in zip(media, media[1:]))
    return {
        "frags": frags,
        "bytes": nbytes,
        "acks_sent": acks,
        "interarrival_p50_ms": round(percentile(intervals, 50), 3),
        "fps": (round(1e3 / percentile(intervals, 50), 2)
                if intervals and percentile(intervals, 50) > 0 else 0.0),
    }


async def run_serving_budget(cfg: Optional[Config] = None,
                             frames: int = 120,
                             width: int = 1920, height: int = 1080,
                             fps: int = 60,
                             probe_link: bool = True,
                             timeout_s: float = 300.0) -> dict:
    """Run the loopback bench and return the ``serving_budget`` block.

    The ledger window is cleared first so the block reflects exactly
    this run; the link probe runs AFTER the media loop so its dispatch
    RTT samples see the same device/tunnel load the frames did.
    """
    import aiohttp

    from .server import bound_port, serve
    from .session import StreamSession

    if cfg is None:
        cfg = serving_budget_config(width, height, fps)
    width, height, fps = cfg.sizew, cfg.sizeh, cfg.refresh

    LEDGER.clear()
    from ..obs import trace as obst
    drops0 = obst.dropped_total()
    loop = asyncio.get_running_loop()
    source = SyntheticSource(width, height, fps=float(fps))
    session = StreamSession(cfg, source, loop=loop)
    session.start()
    runner = await serve(cfg, session)
    sink = {}
    mtext = ""
    cquality: dict = {}
    cdamage = None
    t0 = time.perf_counter()
    try:
        port = bound_port(runner)
        async with aiohttp.ClientSession() as http:
            async with http.ws_connect(
                    f"http://127.0.0.1:{port}/ws",
                    max_msg_size=0) as ws:
                hello = await ws.receive_json(timeout=timeout_s)
                assert hello.get("type") == "hello", hello
                sink = await _drain_ws(
                    ws, frames, timeout_s,
                    has_init=bool(session.init_segment))
            # content-plane visibility (ISSUE 17), captured while the
            # session still serves: the quality gauges on a LIVE
            # /metrics scrape plus the plane's rolling verdict — the
            # keys the CI serving-budget smoke asserts non-empty
            try:
                async with http.get(
                        f"http://127.0.0.1:{port}/metrics") as resp:
                    mtext = await resp.text()
            except Exception:
                mtext = ""
        try:
            from ..obs import content as obsc
            cquality = obsc.PLANE.quality_state().get(
                session.journeys.session) or {}
            cdamage = obsc.PLANE.mean_damage_fraction()
        except Exception:
            cquality, cdamage = {}, None
    finally:
        wall = time.perf_counter() - t0
        # glass-to-glass: captured BEFORE teardown (close_book drops the
        # book); acks closed journeys through the real ws round trip,
        # the rest (unsampled frames) stay open by design
        g2g = session.journeys.summary()
        session.stop()
        await runner.cleanup()

    if probe_link:
        LEDGER.probe_link()
    from ..obs import budget as obsb
    from ..obs import journey as obsj
    block = {
        "mode": "loopback-ws",
        "codec": session.codec_name,
        "geometry": f"{width}x{height}@{fps}",
        "frames_requested": frames,
        "wall_s": round(wall, 2),
        "sink": sink,
        # silent trace loss gate: the serving-budget smoke asserts 0
        # (drops accrued over THIS run, not process lifetime)
        "trace_dropped_total": obst.dropped_total() - drops0,
        # content & quality plane (ISSUE 17): in-graph PSNR/damage must
        # have flowed for this run and be scrapable while serving
        "content": {
            "metrics_visible": (
                "dngd_content_psnr_db" in mtext
                and "dngd_content_damage_fraction" in mtext),
            "psnr_p50_db": cquality.get("psnr_p50"),
            "verdict": cquality.get("verdict"),
            "frames": cquality.get("n", 0),
            "damage_fraction_mean": (round(cdamage, 4)
                                     if cdamage is not None else None),
        },
    }
    # the shared emitter (/debug/budget?format=json renders the same
    # function) — called before close_book so the live journey book is
    # flattened into glass_to_glass; the g2g captured pre-teardown wins
    # if the book already vanished
    block.update(obsb.serving_budget_block(
        session=session.journeys.session))
    if "glass_to_glass" not in block:
        block["glass_to_glass"] = dict(
            g2g, sample_every=obsj.sample_every(),
            methodology=obsb.G2G_METHODOLOGY)
    session.journeys.close_book()
    return block
