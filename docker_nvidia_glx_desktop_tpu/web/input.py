"""Input injection: browser events -> the X desktop.

The reference routes web-client input through selkies' data channel into
xdotool/uinput (deps installed at Dockerfile:419-431; joystick via the
LD_PRELOAD interposer, Dockerfile:473-476).  Here:

- wire protocol: compact CSV messages over the WebSocket data channel
  (``parse_message``), covering pointer move/buttons/wheel, keys (X11
  keysyms, as RFB and the browser's ``KeyboardEvent`` map cleanly onto
  them), and clipboard;
- backends: ``XdotoolBackend`` (X present + xdotool installed — the
  container runtime), ``UinputBackend`` (kernel virtual devices through
  /dev/uinput via raw ioctls — no X needed, used for games/pointer-lock),
  ``FakeBackend`` (records events; tests and headless CI).

``make_injector`` picks the best available backend; every consumer (RFB
server, web server) shares one Injector so button state is consistent.
"""

from __future__ import annotations

import fcntl
import logging
import os
import shutil
import struct
import subprocess
import time
from typing import List, Optional

from ..obs import metrics as obsm

log = logging.getLogger(__name__)

_M_PARSE_ERR = obsm.counter(
    "dngd_input_parse_errors_total",
    "Malformed/rejected input-channel messages by reason", ("reason",))

# Log each rejection REASON once per process — a hostile or buggy client
# spraying garbage must cost one counter bump per message, not a log
# line (the counter is the observable; the first line is the diagnosis).
_logged_reasons: set = set()

__all__ = ["InputBackend", "XdotoolBackend", "UinputBackend", "FakeBackend",
           "Injector", "make_injector", "parse_message"]


class InputBackend:
    def move(self, x: int, y: int) -> None: ...
    def move_rel(self, dx: int, dy: int) -> None:
        """Relative pointer motion (the pointer-lock path: games/CAD need
        raw deltas, not absolute positions)."""
    def button(self, button: int, down: bool) -> None: ...
    def wheel(self, dy: int) -> None: ...
    def key(self, keysym: int, down: bool) -> None: ...
    def set_clipboard(self, text: str) -> None: ...
    def get_clipboard(self) -> Optional[str]:
        """Desktop -> client clipboard direction (xclip -o); None when
        unsupported."""
        return None
    def close(self) -> None: ...


class FakeBackend(InputBackend):
    """Records every call — the test double."""

    def __init__(self):
        self.events: List[tuple] = []

    def move(self, x, y):
        self.events.append(("move", x, y))

    def move_rel(self, dx, dy):
        self.events.append(("move_rel", dx, dy))

    def button(self, button, down):
        self.events.append(("button", button, down))

    def wheel(self, dy):
        self.events.append(("wheel", dy))

    def key(self, keysym, down):
        self.events.append(("key", keysym, down))

    def set_clipboard(self, text):
        self.events.append(("clipboard", text))
        self._clipboard = text

    def get_clipboard(self):
        return getattr(self, "_clipboard", None)


class XdotoolBackend(InputBackend):
    """Inject through xdotool (reference Dockerfile:419) — X required."""

    def __init__(self, display: str = ":0"):
        if shutil.which("xdotool") is None:
            raise RuntimeError("xdotool not installed")
        self.env = dict(os.environ, DISPLAY=display)

    def _run(self, *args: str) -> None:
        subprocess.run(["xdotool", *args], env=self.env,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                       timeout=5, check=False)

    def move(self, x, y):
        self._run("mousemove", str(x), str(y))

    def move_rel(self, dx, dy):
        self._run("mousemove_relative", "--", str(dx), str(dy))

    def button(self, button, down):
        self._run("mousedown" if down else "mouseup", str(button))

    def wheel(self, dy):
        self._run("click", "4" if dy > 0 else "5")

    def key(self, keysym, down):
        # xdotool accepts numeric keysyms as 0xNNNN names via `key --`.
        name = f"0x{keysym:04x}"
        self._run("keydown" if down else "keyup", name)

    def set_clipboard(self, text):
        if shutil.which("xclip"):
            p = subprocess.Popen(["xclip", "-selection", "clipboard"],
                                 stdin=subprocess.PIPE, env=self.env)
            p.communicate(text.encode(), timeout=5)

    def get_clipboard(self):
        if shutil.which("xclip") is None:
            return None
        try:
            out = subprocess.run(
                ["xclip", "-selection", "clipboard", "-o"], env=self.env,
                capture_output=True, timeout=5)
            return out.stdout.decode("utf-8", "replace") \
                if out.returncode == 0 else None
        except subprocess.SubprocessError:
            return None


# --- uinput: virtual mouse + keyboard via raw ioctls ------------------------

_UI_SET_EVBIT = 0x40045564
_UI_SET_KEYBIT = 0x40045565
_UI_SET_RELBIT = 0x40045566
_UI_SET_ABSBIT = 0x40045567
_UI_DEV_CREATE = 0x5501
_UI_DEV_DESTROY = 0x5502
_EV_SYN, _EV_KEY, _EV_REL, _EV_ABS = 0x00, 0x01, 0x02, 0x03
_REL_X, _REL_Y, _REL_WHEEL = 0x00, 0x01, 0x08
_ABS_X, _ABS_Y = 0x00, 0x01
_BTN_LEFT, _BTN_RIGHT, _BTN_MIDDLE = 0x110, 0x111, 0x112
_BTN_TOUCH = 0x14A
_ABS_CNT = 64  # ABS_CNT in linux/input.h (sizes the 4 abs arrays)

# Minimal X11 keysym -> Linux KEY_* map (ASCII letters/digits + controls).
_KEYSYM_TO_KEY = {
    0xFF0D: 28, 0xFF1B: 1, 0xFF08: 14, 0xFF09: 15, 0x0020: 57,
    0xFFE1: 42, 0xFFE2: 54, 0xFFE3: 29, 0xFFE4: 97, 0xFFE9: 56, 0xFFEA: 100,
    0xFF51: 105, 0xFF52: 103, 0xFF53: 106, 0xFF54: 108,
    0xFF50: 102, 0xFF57: 107, 0xFF55: 104, 0xFF56: 109, 0xFFFF: 111,
}
for i, ch in enumerate("1234567890"):
    _KEYSYM_TO_KEY[ord(ch)] = 2 + i
for i, ch in enumerate("qwertyuiop"):
    _KEYSYM_TO_KEY[ord(ch)] = 16 + i
    _KEYSYM_TO_KEY[ord(ch.upper())] = 16 + i
for i, ch in enumerate("asdfghjkl"):
    _KEYSYM_TO_KEY[ord(ch)] = 30 + i
    _KEYSYM_TO_KEY[ord(ch.upper())] = 30 + i
for i, ch in enumerate("zxcvbnm"):
    _KEYSYM_TO_KEY[ord(ch)] = 44 + i
    _KEYSYM_TO_KEY[ord(ch.upper())] = 44 + i


class UinputBackend(InputBackend):
    """Kernel-level virtual input device (works with no X server).

    The struct layouts are the stable linux/uinput.h ABI:
    ``struct uinput_user_dev`` (name[80] + id + ff_effects + 4x abs arrays)
    and ``struct input_event`` (timeval + type + code + value).
    """

    def __init__(self, path: str = "/dev/uinput",
                 width: int = 4096, height: int = 4096):
        """``width``/``height``: ABS coordinate range — pointer positions are
        absolute (EV_ABS), so desktop pointer acceleration cannot desync the
        cursor from the client's coordinates (a REL_X/REL_Y design would)."""
        self.fd = os.open(path, os.O_WRONLY | os.O_NONBLOCK)
        for ev in (_EV_KEY, _EV_REL, _EV_ABS, _EV_SYN):
            fcntl.ioctl(self.fd, _UI_SET_EVBIT, ev)
        for rb in (_REL_X, _REL_Y, _REL_WHEEL):
            fcntl.ioctl(self.fd, _UI_SET_RELBIT, rb)
        for ab in (_ABS_X, _ABS_Y):
            fcntl.ioctl(self.fd, _UI_SET_ABSBIT, ab)
        for code in (_BTN_LEFT, _BTN_RIGHT, _BTN_MIDDLE, _BTN_TOUCH,
                     *set(_KEYSYM_TO_KEY.values())):
            fcntl.ioctl(self.fd, _UI_SET_KEYBIT, code)
        name = b"tpu-desktop-virtual-input"
        dev = struct.pack("80sHHHHi", name.ljust(80, b"\0"),
                          0x03, 0x1234, 0x5678, 1, 0)
        absmax = [0] * _ABS_CNT
        absmax[_ABS_X], absmax[_ABS_Y] = width - 1, height - 1
        dev += struct.pack(f"{_ABS_CNT}i", *absmax)   # absmax
        dev += b"\0" * (_ABS_CNT * 4 * 3)             # absmin/fuzz/flat
        os.write(self.fd, dev)
        fcntl.ioctl(self.fd, _UI_DEV_CREATE)

    def _emit(self, etype: int, code: int, value: int) -> None:
        now = time.time()
        sec, usec = int(now), int((now % 1) * 1e6)
        os.write(self.fd, struct.pack("llHHi", sec, usec, etype, code, value))

    def _syn(self):
        self._emit(_EV_SYN, 0, 0)

    def move(self, x, y):
        self._emit(_EV_ABS, _ABS_X, x)
        self._emit(_EV_ABS, _ABS_Y, y)
        self._syn()

    def move_rel(self, dx, dy):
        if dx:
            self._emit(_EV_REL, _REL_X, dx)
        if dy:
            self._emit(_EV_REL, _REL_Y, dy)
        self._syn()

    def button(self, button, down):
        code = {1: _BTN_LEFT, 2: _BTN_MIDDLE, 3: _BTN_RIGHT}.get(button)
        if code is not None:
            self._emit(_EV_KEY, code, int(down))
            self._syn()

    def wheel(self, dy):
        self._emit(_EV_REL, _REL_WHEEL, 1 if dy > 0 else -1)
        self._syn()

    def key(self, keysym, down):
        code = _KEYSYM_TO_KEY.get(keysym)
        if code is not None:
            self._emit(_EV_KEY, code, int(down))
            self._syn()

    def set_clipboard(self, text):
        pass  # clipboard has no kernel path; X backend handles it

    def close(self):
        try:
            fcntl.ioctl(self.fd, _UI_DEV_DESTROY)
        finally:
            os.close(self.fd)


# --- the injector: protocol -> backend --------------------------------------

# Hardening bounds (the parser feeds an unauthenticated-after-join wire:
# a malformed or hostile message must cost a counter bump, never an
# exception escaping the channel callback or unbounded memory).  The
# whole-message cap IS the data channel's negotiated max-message-size
# (webrtc/sdp.MAX_MESSAGE_SIZE — kept numerically in sync, asserted in
# tests): a clipboard the parser accepts must also be SENDABLE as one
# channel message, so the decoded cap derives from the same budget.
MAX_MESSAGE_CHARS = 262_144           # = sdp.MAX_MESSAGE_SIZE
MAX_CLIPBOARD_B64 = MAX_MESSAGE_CHARS - 8     # minus "c," + slack
MAX_CLIPBOARD_TEXT = MAX_CLIPBOARD_B64 // 4 * 3   # base64 3->4
MAX_FIELD_CHARS = 12                  # numeric fields (int() cost bound)
_COORD_LIMIT = 1 << 16                # sane screen-coordinate envelope


def _reject(reason: str, msg: str) -> None:
    _M_PARSE_ERR.labels(reason).inc()
    if reason not in _logged_reasons:
        _logged_reasons.add(reason)
        log.warning("input message rejected (%s): %.64r "
                    "(logged once per reason; see "
                    "dngd_input_parse_errors_total)", reason, msg)


def _int_field(s: str, limit: int = _COORD_LIMIT) -> int:
    """Bounded numeric field: length-capped before int() and range-
    clamped after (a 1 MB digit string or a 10^30 coordinate is garbage,
    not input)."""
    if len(s) > MAX_FIELD_CHARS:
        raise ValueError("field too long")
    v = int(s)
    if not -limit <= v <= limit:
        raise ValueError("field out of range")
    return v


def parse_message(msg: str) -> Optional[dict]:
    """Parse one data-channel input message; None (counted, log-once)
    on anything malformed, truncated or oversized — this function never
    raises (it sits inside the channel delivery callback).

    Wire format (CSV, first field = op):
      ``m,<x>,<y>``            pointer move (absolute)
      ``mr,<dx>,<dy>``         pointer move (relative; pointer lock)
      ``b,<button>,<0|1>``     pointer button (1=left 2=middle 3=right)
      ``s,<dy>``               scroll wheel
      ``k,<keysym>,<0|1>``     key up/down (X11 keysym, decimal)
      ``c,<base64 text>``      clipboard set (bounded, see
                               MAX_CLIPBOARD_TEXT)
      ``r,<w>x<h>``            resize request (WEBRTC_ENABLE_RESIZE)
      ``kf``                   force keyframe (IDR) request
    """
    try:
        if not isinstance(msg, str):
            _reject("not-text", repr(type(msg)))
            return None
        if len(msg) > MAX_MESSAGE_CHARS:
            _reject("oversized", msg[:64])
            return None
        parts = msg.strip().split(",")
        op = parts[0]
        try:
            if op == "m":
                return {"type": "move", "x": _int_field(parts[1]),
                        "y": _int_field(parts[2])}
            if op == "mr":
                return {"type": "move_rel", "dx": _int_field(parts[1]),
                        "dy": _int_field(parts[2])}
            if op == "b":
                return {"type": "button", "button": _int_field(parts[1]),
                        "down": parts[2] == "1"}
            if op == "s":
                return {"type": "wheel", "dy": _int_field(parts[1])}
            if op == "k":
                # XF86 keysyms reach 0x1008FFFF; 2^31 bounds them all
                return {"type": "key",
                        "keysym": _int_field(parts[1], 1 << 31),
                        "down": parts[2] == "1"}
            if op == "c":
                import base64
                payload = parts[1] if len(parts) > 1 else ""
                if len(payload) > MAX_CLIPBOARD_B64:
                    _reject("clipboard-oversized", msg[:64])
                    return None
                text = base64.b64decode(payload).decode("utf-8",
                                                        "replace")
                if len(text.encode("utf-8")) > MAX_CLIPBOARD_TEXT:
                    _reject("clipboard-oversized", msg[:64])
                    return None
                return {"type": "clipboard", "text": text}
            if op == "r":
                w, h = parts[1].split("x")
                return {"type": "resize", "width": _int_field(w),
                        "height": _int_field(h)}
            if op == "kf":
                return {"type": "keyframe"}
            _reject("unknown-op", msg)
        except (IndexError, ValueError):
            _reject("malformed", msg)
        return None
    except Exception:                 # pragma: no cover - belt & braces
        log.exception("input parser internal error")
        _M_PARSE_ERR.labels("internal").inc()
        return None


class Injector:
    """Routes parsed events into a backend; adapts RFB's stateful masks."""

    def __init__(self, backend: InputBackend):
        self.backend = backend
        self._rfb_buttons = 0

    def handle(self, event: dict) -> None:
        t = event.get("type")
        if t == "move":
            self.backend.move(event["x"], event["y"])
        elif t == "move_rel":
            self.backend.move_rel(event["dx"], event["dy"])
        elif t == "button":
            self.backend.button(event["button"], event["down"])
        elif t == "wheel":
            self.backend.wheel(event["dy"])
        elif t == "key":
            self.backend.key(event["keysym"], event["down"])
        elif t == "clipboard":
            self.backend.set_clipboard(event["text"])

    def handle_message(self, msg: str) -> Optional[dict]:
        event = parse_message(msg)
        if event is not None:
            self.handle(event)
        return event

    def read_clipboard(self) -> Optional[str]:
        """Desktop -> client direction (selkies reads xclip both ways)."""
        return self.backend.get_clipboard()

    def handle_rfb(self, event: dict) -> None:
        """RFB PointerEvent carries a button *mask*; diff it into presses."""
        if event["type"] == "pointer":
            self.backend.move(event["x"], event["y"])
            changed = event["buttons"] ^ self._rfb_buttons
            for bit in range(8):
                if changed & (1 << bit):
                    down = bool(event["buttons"] & (1 << bit))
                    if bit in (3, 4):            # RFB wheel pseudo-buttons
                        if down:
                            self.backend.wheel(1 if bit == 3 else -1)
                    else:
                        self.backend.button(bit + 1, down)
            self._rfb_buttons = event["buttons"]
        elif event["type"] == "key":
            self.backend.key(event["keysym"], event["down"])
        elif event["type"] == "cuttext":
            self.backend.set_clipboard(event["text"])


def make_injector(display: str = ":0") -> Injector:
    """Best available backend: xdotool (X) > uinput (kernel) > fake."""
    try:
        return Injector(XdotoolBackend(display))
    except Exception:
        pass
    try:
        return Injector(UinputBackend())
    except Exception:
        pass
    return Injector(FakeBackend())
