"""Stock-selkies web-client signaling compatibility shim.

SURVEY §2.2 E2 set "behavior-compatible with the selkies web client"
as the rebuild bar; the first-party client speaks its own (simpler)
protocol.  This adapter translates the selkies-gstreamer signaling
schema onto the existing session machinery so an UNMODIFIED selkies
web app can negotiate and stream (VERDICT r4 item 10; the web app the
reference actually serves, reference
selkies-gstreamer-entrypoint.sh:43-47):

  client -> ``HELLO <peer_id> <btoa(meta)>``     server -> ``HELLO``
  server -> ``{"sdp": {"type": "offer", ...}}``  (role inversion: the
            selkies APP's webrtcbin creates the offer — see
            WebRtcPeer.create_offer)
  client -> ``{"sdp": {"type": "answer", ...}}``
  client -> ``{"ice": {"candidate": ...}}``      (trickle; feeds TURN
            permissions — our ICE-lite learns the pair from checks)
  server -> ``{"ice": ...}`` never sent (candidates ride the offer,
            which ends with a=end-of-candidates)

Mounted at ``/<app>/signalling/`` for any app name plus the literal
``/signalling`` (the stock client derives the path from its app name).

Input: selkies carries input/clipboard/stats over SCTP data channels on
the media DTLS association.  The offer negotiates
``m=application webrtc-datachannel`` (webrtc/sdp.build_offer), the
first-party SCTP/DCEP stack (webrtc/sctp + webrtc/datachannel)
terminates the channels, and :func:`attach_input_channels` routes their
messages into the same CSV parser and X injection path the WebSocket
input uses (web/input) — an unmodified selkies client's keystrokes land
on the desktop byte-for-byte identically to the first-party client's.
"""

from __future__ import annotations

import json
import logging

from aiohttp import WSMsgType, web

from ..obs import metrics as obsm
from ..resilience import ingress as ringress

log = logging.getLogger(__name__)

__all__ = ["register_selkies_routes", "attach_input_channels",
           "ingest_client_qoe", "drop_client_qoe"]

_M_INPUT_DROPPED = obsm.counter(
    "dngd_datachannel_input_dropped_total",
    "Channel input messages dropped by the bounded per-peer queue")

# -- client-side QoE (ISSUE 17 satellite): the decode half of
# glass-to-glass.  The stock selkies HUD (and the first-party client)
# can push periodic reports over the stats channel; whatever of the
# rendered-fps / decode-time / jitter-buffer trio a client reports
# lands on per-peer gauges next to the server-side content plane.
_M_QOE = obsm.gauge(
    "dngd_client_qoe",
    "Client-reported playback QoE over the stats data channel "
    "(stat=fps|decode_ms|jitter_buffer_ms)", ("peer", "stat"))
_M_QOE_REPORTS = obsm.counter(
    "dngd_client_qoe_reports_total",
    "Client QoE reports ingested from the stats data channel",
    ("peer",))

# tolerant field map: selkies-gstreamer HUD names, webrtc getStats
# names, and the obvious snake_case spellings all land on one stat
_QOE_FIELDS = {
    "fps": ("fps", "framerate", "framespersecond", "renderedfps",
            "framesperseconddecoded", "frameratedecoded"),
    "decode_ms": ("decode_ms", "decodetime", "decodetimems",
                  "framedecodetime", "videodecodetime"),
    "jitter_buffer_ms": ("jitter_buffer_ms", "jitterbuffer",
                         "jitterbufferms", "jitterbufferdelay",
                         "jitterbufferdelayms"),
}


def _qoe_scan(obj, found: dict, depth: int = 0) -> None:
    """Collect recognized QoE numbers from a (possibly nested) report."""
    if depth > 2 or not isinstance(obj, dict):
        return
    for k, v in obj.items():
        if isinstance(v, dict):
            _qoe_scan(v, found, depth + 1)
            continue
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        key = str(k).replace("_", "").replace("-", "").lower()
        for stat, names in _QOE_FIELDS.items():
            if key in names and stat not in found:
                try:
                    found[stat] = float(v)
                except OverflowError:
                    # JSON ints are arbitrary precision; a 10**400
                    # "fps" must land as a droppable non-finite, not
                    # an uncaught raise in the channel callback
                    found[stat] = float("inf")


# sane-range clamps for client-reported numbers (ISSUE 18 satellite:
# the client is untrusted — an absurd report must not poison the QoE
# dashboards the fleet plane reads next to the server-side content
# stats).  Values clamp into range; non-finite values drop.
_QOE_CLAMPS = {
    "fps": (0.0, 1000.0),
    "decode_ms": (0.0, 10_000.0),
    "jitter_buffer_ms": (0.0, 10_000.0),
}
# bound the per-peer label population independently of the registry's
# global cardinality cap: past this many distinct reporting peers, new
# ones collapse onto one "other" series instead of minting their own
_QOE_PEER_CAP = 32
_qoe_peer_names: set = set()


def ingest_client_qoe(peer_name: str, msg, budget=None) -> bool:
    """Ingest one stats-channel message's QoE fields into the per-peer
    gauges; returns True when the message carried any (i.e. it was a
    client report, not a HUD poll).  ``budget`` (resilience/ingress)
    rate-limits reports and scores out-of-range values."""
    found: dict = {}
    _qoe_scan(msg, found)
    if not found:
        return False
    if budget is not None and (not budget.allow_nonmedia()
                               or not budget.charge("qoe")):
        return True          # it WAS a QoE report; it just doesn't land
    if peer_name not in _qoe_peer_names:
        if len(_qoe_peer_names) >= _QOE_PEER_CAP:
            peer_name = "other"
        else:
            _qoe_peer_names.add(peer_name)
    for stat, v in found.items():
        lo, hi = _QOE_CLAMPS.get(stat, (0.0, 1e6))
        if not (v == v and -1e18 < v < 1e18):     # NaN / inf
            if budget is not None:
                budget.violation("qoe_insane", weight=0.5)
            continue
        if v < lo or v > hi:
            if budget is not None:
                budget.violation("qoe_insane", weight=0.25)
            v = min(max(v, lo), hi)
        _M_QOE.labels(peer_name, stat).set(v)
    _M_QOE_REPORTS.labels(peer_name).inc()
    return True


def drop_client_qoe(peer_name: str) -> None:
    """Peer teardown: stale per-peer QoE series must not outlive the
    connection (metrics cardinality contract)."""
    for stat in _QOE_FIELDS:
        _M_QOE.remove(peer_name, stat)
    _M_QOE_REPORTS.remove(peer_name)
    _qoe_peer_names.discard(peer_name)

# A flooding client must cost a counter bump, not unbounded memory: the
# /ws path gets natural backpressure from its sequential read loop; the
# channel path bounds its queue instead (injection drains via a
# subprocess-speed executor, so depth = seconds of typing burst).
INPUT_QUEUE_DEPTH = 1024


def attach_input_channels(peer, session, injector, loop=None) -> None:
    """Bind the selkies data channels on ``peer``.

    - ``input`` (and any unrecognized label — selkies multiplexes its
      whole control plane over one channel): each string message is one
      compact CSV input event, fed through the SAME parser + executor-
      offloaded injection path as the WebSocket input
      (server.handle_input_text), so the two transports are
      byte-for-byte identical at the X boundary;
    - ``clipboard``: raw base64 text -> bounded clipboard set (reuses
      the parser's ``c,`` op and its hardening caps);
    - ``stats``: any message answers with the live session stats JSON
      (the selkies HUD poll).
    """
    import asyncio

    from .server import handle_input_text, spawn_bg

    # One serialized worker per peer: channel callbacks enqueue, a
    # single consumer injects — keystroke ORDER is part of the input
    # contract, and concurrent executor hops would race it.  The worker
    # spawns lazily on the first channel and dies with the peer (the
    # close hook cancels it; tasks are strong-ref'd via spawn_bg).
    state = {"queue": None, "task": None}

    def _enqueue(text: str) -> None:
        if state["queue"] is None:
            state["queue"] = asyncio.Queue(maxsize=INPUT_QUEUE_DEPTH)

            async def worker():
                try:
                    while True:
                        t = await state["queue"].get()
                        try:
                            await handle_input_text(t, session,
                                                    injector, loop)
                        except Exception:
                            # a wedged backend (xdotool TimeoutExpired)
                            # must cost one event, not kill the worker
                            # and silently deaden input for the session
                            log.exception("channel input injection "
                                          "failed; message dropped")
                except asyncio.CancelledError:
                    pass

            state["task"] = spawn_bg(worker())
            hooks = getattr(peer, "close_hooks", None)
            if hooks is not None:
                hooks.append(state["task"].cancel)
        try:
            state["queue"].put_nowait(text)
        except asyncio.QueueFull:
            # drop-and-count, like the parser's hardening: newest lost
            # under flood beats unbounded growth (a real typist cannot
            # outrun a 1024-deep queue)
            _M_INPUT_DROPPED.inc()

    # the WS handler routes its input through the SAME worker once a
    # peer is bound (server._handle_client_msg): events spanning the
    # WS -> data-channel switchover (a drag whose press went over /ws
    # and release over the channel) must not be injected by two
    # concurrent executor hops in arbitrary order
    peer.input_enqueue = _enqueue

    peer_name = str(getattr(peer, "peer_id", "")
                    or f"peer-{id(peer) & 0xffffff:x}")
    hooks0 = getattr(peer, "close_hooks", None)
    if hooks0 is not None:
        hooks0.append(lambda: drop_client_qoe(peer_name))

    def on_channel(channel) -> None:
        label = (channel.label or "").lower()

        if label.startswith("stats"):
            def on_stats(_data, _ch=channel):
                try:
                    text = (_data if isinstance(_data, str)
                            else _data.decode("utf-8", "replace"))
                    # first-party glass-to-glass ack over the stats
                    # channel: {"type": "ack", "frame_id"|"id": N}
                    # closes the frame's journey at server receipt
                    # (obs/journey); a client QoE report (rendered
                    # fps / decode time / jitter-buffer delay) feeds
                    # the per-peer dngd_client_qoe gauges; anything
                    # else is the selkies HUD poll and gets the live
                    # stats JSON back
                    if text.startswith("{"):
                        try:
                            msg = json.loads(text)
                        except ValueError:
                            msg = None
                        budget = getattr(peer, "ingress_budget", None)
                        if msg and msg.get("type") == "ack":
                            # same gating as the /ws ack path: only a
                            # fid from THIS connection's outstanding
                            # probe window may close a journey —
                            # spoofed/replayed ids are violations, not
                            # fabricated g2g samples
                            if budget is not None and \
                                    not budget.charge("ack"):
                                return
                            try:
                                fid = int(msg.get("frame_id",
                                                  msg.get("id")) or 0)
                            except (TypeError, ValueError):
                                if budget is not None:
                                    budget.violation("ack_spoof",
                                                     weight=0.5)
                                return
                            probes = getattr(peer, "ingress_probes",
                                             None)
                            if probes is not None and \
                                    not probes.take(fid):
                                if budget is not None:
                                    budget.violation("ack_spoof",
                                                     weight=0.5)
                                return
                            book = getattr(session, "journeys", None)
                            if book is not None:
                                book.close(fid, method="client")
                            return
                        if msg and ingest_client_qoe(peer_name, msg,
                                                     budget=budget):
                            return
                    payload = (session.stats_summary()
                               if hasattr(session, "stats_summary")
                               else {})
                    _ch.send(json.dumps({"type": "stats",
                                         "data": payload}))
                except Exception:
                    log.exception("stats channel reply failed")

            channel.on_message = on_stats
            return

        if label.startswith("clipboard"):
            def on_clip(data):
                text = (data if isinstance(data, str)
                        else data.decode("utf-8", "replace"))
                _enqueue(f"c,{text}")

            channel.on_message = on_clip
            return

        # "input" and anything else: the CSV input protocol
        def on_input(data):
            text = (data if isinstance(data, str)
                    else data.decode("utf-8", "replace"))
            _enqueue(text)

        channel.on_message = on_input

    peer.on_datachannel = on_channel


async def _signalling_handler(request: web.Request, session, audio,
                              conn_turn, advertise_ip: str,
                              injector=None):
    import asyncio

    ws = web.WebSocketResponse(heartbeat=20.0, max_msg_size=0)
    await ws.prepare(request)
    loop = asyncio.get_running_loop()
    peer = None
    on_au = on_audio = None
    negotiated = False
    # zero-downtime handoff (resilience/handoff): same contract as /ws —
    # a ?resume= token redeems the predecessor's wire continuity, and
    # this connection registers for the NEXT migration.  The stock
    # protocol is untouched; the token and migrate notice ride shim-only
    # JSON keys ({"resume": ...} / {"migrate": ...}) a stock client
    # ignores and a shim-aware client honors.
    hmgr = request.app.get("handoff")
    resume_entry = None
    handoff_token = None
    if hmgr is not None and hmgr.enabled:
        tok = request.query.get("resume")
        if tok:
            resume_entry = hmgr.claim(tok)

        def _notify_migrate(new_tok, retry_s, _ws=ws):
            async def _go():
                try:
                    await _ws.send_str(json.dumps(
                        {"migrate": {"resume": new_tok,
                                     "retry_after_s": round(retry_s,
                                                            2)}}))
                except Exception:
                    pass
            from .server import spawn_bg
            spawn_bg(_go())

        handoff_token = hmgr.register(
            sid=f"selkies-{request.remote or 'local'}",
            notify=_notify_migrate)
    # trust boundary (resilience/ingress): one governor + one probe
    # window per signalling connection, shared by every peer it
    # negotiates.  EVICT closes the socket with the selkies error shape.
    probes = ringress.ProbeWindow()

    def _ingress_evict(bud, reason, _ws=ws):
        async def _go():
            try:
                await _ws.send_str(json.dumps(
                    {"error": "evicted: protocol violations"}))
                await _ws.close()
            except Exception:
                pass
        from .server import spawn_bg
        spawn_bg(_go())

    budget = ringress.PeerBudget(
        f"selkies-{request.remote or 'local'}", on_evict=_ingress_evict)

    def teardown_peer():
        nonlocal peer, on_au, on_audio, negotiated
        if on_au is not None:
            session.remove_au_listener(on_au)
            on_au = None
        if on_audio is not None and audio is not None:
            audio.remove_listener(on_audio)
            on_audio = None
        if peer is not None:
            peer.close()
            peer = None
        negotiated = False

    try:
        async for msg in ws:
            if msg.type != WSMsgType.TEXT:
                if msg.type in (WSMsgType.CLOSE, WSMsgType.ERROR):
                    break
                continue
            text = msg.data
            if text.startswith("HELLO"):
                teardown_peer()      # a re-HELLO restarts negotiation
                await ws.send_str("HELLO")
                if handoff_token is not None:
                    # shim extension: the resume token for the NEXT
                    # process handoff (stock clients ignore it)
                    await ws.send_str(json.dumps(
                        {"resume": handoff_token}))
                # role inversion: WE offer now
                from ..webrtc.peer import WebRtcPeer

                codec_name = getattr(session, "codec_name", "")
                rtc_codec = ("H264" if codec_name.startswith("h264")
                             else "VP8" if codec_name.startswith("vp8")
                             else None)
                if rtc_codec is None or not hasattr(session,
                                                    "add_au_listener"):
                    await ws.send_str(json.dumps(
                        {"error": f"codec {codec_name!r} not "
                                  "RTC-streamable"}))
                    continue
                rtc_audio = (audio is not None
                             and getattr(audio, "format", "") == "opus")
                peer = WebRtcPeer(clock=getattr(session, "clock", None),
                                  video_codec=rtc_codec,
                                  advertise_ip=advertise_ip,
                                  with_audio=rtc_audio,
                                  turn=conn_turn)
                # RTCP-fallback journey closure for the stock client
                peer.journeys = getattr(session, "journeys", None)
                peer.set_ingress_budget(budget)
                peer.ingress_probes = probes
                # stock-client PLI/FIR -> the session's rate-limited
                # IDR path (dedupes against the degrade ladder rung)
                from .session import keyframe_requester
                peer.on_keyframe_request = keyframe_requester(session)
                # bind input/clipboard/stats BEFORE any DCEP can arrive
                sess_injector = getattr(session, "injector", None) \
                    or injector
                attach_input_channels(peer, session, sess_injector,
                                      loop=loop)
                if resume_entry is not None and resume_entry.get("wire"):
                    # resumed client: the offer must carry the SSRCs it
                    # was already decoding on the predecessor
                    peer.import_wire(resume_entry["wire"])
                    resume_entry = None          # single-shot
                if handoff_token is not None and hmgr is not None:
                    hmgr.attach_wire(handoff_token, peer.export_wire)
                offer_sdp = await peer.create_offer()
                if request.remote:
                    await peer.add_remote_candidate_ip(request.remote)
                await ws.send_str(json.dumps(
                    {"sdp": {"type": "offer", "sdp": offer_sdp}}))
                continue
            if not text.startswith("{"):
                continue
            if not budget.allow_nonmedia():
                # flooding through the quarantine cooldown climbs the
                # ladder toward eviction (same contract as /ws)
                budget.violation("quarantine_ingest", weight=0.2)
                continue
            if not budget.charge("signal"):
                continue
            try:
                data = json.loads(text)
            except ValueError:
                budget.violation("signal_bad_json")
                continue
            if not isinstance(data, dict):
                budget.violation("signal_bad_json", weight=0.5)
                continue
            if "sdp" in data and peer is not None:
                sd = data["sdp"]
                if not isinstance(sd, dict):
                    budget.violation("signal_bad_json", weight=0.5)
                    continue
                if sd.get("type") == "answer" and not negotiated:
                    from ..webrtc.sdp import SdpError
                    try:
                        await peer.handle_answer(sd.get("sdp", ""))
                    except SdpError as e:
                        # hostile/corrupt answer: reject cleanly and
                        # leave the offer on the table for a retry
                        # instead of unwinding the whole /signalling
                        # handler
                        log.warning("answer rejected at trust "
                                    "boundary: %s (%s)", e.reason, e)
                        budget.violation(e.reason, weight=5.0)
                        await ws.send_str(json.dumps(
                            {"error": f"bad answer: {e.reason}"}))
                        continue
                    negotiated = True

                    def on_au(au, keyframe, pts, _p=peer):
                        _p.send_video_au(au, pts)

                    session.add_au_listener(on_au)
                    if (audio is not None
                            and getattr(audio, "format", "") == "opus"):
                        def on_audio(pts, packet, _p=peer):
                            _p.send_audio(packet, pts)

                        audio.add_listener(on_audio)
                    if hasattr(session, "request_keyframe"):
                        peer.on_ready = session.request_keyframe
            elif "ice" in data and peer is not None:
                cand = data["ice"] or {}
                line = cand.get("candidate", "") if isinstance(
                    cand, dict) else ""
                parts = line.split()
                if len(parts) >= 5:
                    await peer.add_remote_candidate_ip(parts[4])
    finally:
        if handoff_token is not None and hmgr is not None:
            hmgr.detach(handoff_token)
        teardown_peer()
        budget.close()
    return ws


def register_selkies_routes(app: web.Application, cfg, session,
                            audio, injector=None) -> None:
    """Mount the shim at /signalling and /{app}/signalling (both with
    and without trailing slash — the stock client builds the URL from
    its app name).  ``injector`` is the shared input path the data
    channels feed (falls back to ``session.injector`` per hub)."""
    from .turn import server_turn_config

    async def handler(request: web.Request):
        sockname = (request.transport.get_extra_info("sockname")
                    if request.transport is not None else None)
        advertise_ip = sockname[0] if sockname else "127.0.0.1"
        return await _signalling_handler(
            request, session, audio, server_turn_config(cfg),
            advertise_ip, injector=injector)

    app.router.add_get("/signalling", handler)
    app.router.add_get("/signalling/", handler)
    app.router.add_get("/{app_name}/signalling", handler)
    app.router.add_get("/{app_name}/signalling/", handler)
