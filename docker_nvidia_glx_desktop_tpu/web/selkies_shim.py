"""Stock-selkies web-client signaling compatibility shim.

SURVEY §2.2 E2 set "behavior-compatible with the selkies web client"
as the rebuild bar; the first-party client speaks its own (simpler)
protocol.  This adapter translates the selkies-gstreamer signaling
schema onto the existing session machinery so an UNMODIFIED selkies
web app can negotiate and stream (VERDICT r4 item 10; the web app the
reference actually serves, reference
selkies-gstreamer-entrypoint.sh:43-47):

  client -> ``HELLO <peer_id> <btoa(meta)>``     server -> ``HELLO``
  server -> ``{"sdp": {"type": "offer", ...}}``  (role inversion: the
            selkies APP's webrtcbin creates the offer — see
            WebRtcPeer.create_offer)
  client -> ``{"sdp": {"type": "answer", ...}}``
  client -> ``{"ice": {"candidate": ...}}``      (trickle; feeds TURN
            permissions — our ICE-lite learns the pair from checks)
  server -> ``{"ice": ...}`` never sent (candidates ride the offer,
            which ends with a=end-of-candidates)

Mounted at ``/<app>/signalling/`` for any app name plus the literal
``/signalling`` (the stock client derives the path from its app name).

Known gap, documented: selkies carries input/clipboard/stats over an
SCTP data channel; this stack has no SCTP, so a stock client views and
hears the session but its input events do not arrive.  The first-party
client (served at /) has full input over the websocket.
"""

from __future__ import annotations

import json
import logging

from aiohttp import WSMsgType, web

log = logging.getLogger(__name__)

__all__ = ["register_selkies_routes"]


async def _signalling_handler(request: web.Request, session, audio,
                              conn_turn, advertise_ip: str):
    ws = web.WebSocketResponse(heartbeat=20.0, max_msg_size=0)
    await ws.prepare(request)
    peer = None
    on_au = on_audio = None
    negotiated = False

    def teardown_peer():
        nonlocal peer, on_au, on_audio, negotiated
        if on_au is not None:
            session.remove_au_listener(on_au)
            on_au = None
        if on_audio is not None and audio is not None:
            audio.remove_listener(on_audio)
            on_audio = None
        if peer is not None:
            peer.close()
            peer = None
        negotiated = False

    try:
        async for msg in ws:
            if msg.type != WSMsgType.TEXT:
                if msg.type in (WSMsgType.CLOSE, WSMsgType.ERROR):
                    break
                continue
            text = msg.data
            if text.startswith("HELLO"):
                teardown_peer()      # a re-HELLO restarts negotiation
                await ws.send_str("HELLO")
                # role inversion: WE offer now
                from ..webrtc.peer import WebRtcPeer

                codec_name = getattr(session, "codec_name", "")
                rtc_codec = ("H264" if codec_name.startswith("h264")
                             else "VP8" if codec_name.startswith("vp8")
                             else None)
                if rtc_codec is None or not hasattr(session,
                                                    "add_au_listener"):
                    await ws.send_str(json.dumps(
                        {"error": f"codec {codec_name!r} not "
                                  "RTC-streamable"}))
                    continue
                rtc_audio = (audio is not None
                             and getattr(audio, "format", "") == "opus")
                peer = WebRtcPeer(clock=getattr(session, "clock", None),
                                  video_codec=rtc_codec,
                                  advertise_ip=advertise_ip,
                                  with_audio=rtc_audio,
                                  turn=conn_turn)
                offer_sdp = await peer.create_offer()
                if request.remote:
                    await peer.add_remote_candidate_ip(request.remote)
                await ws.send_str(json.dumps(
                    {"sdp": {"type": "offer", "sdp": offer_sdp}}))
                continue
            if not text.startswith("{"):
                continue
            try:
                data = json.loads(text)
            except ValueError:
                continue
            if "sdp" in data and peer is not None:
                sd = data["sdp"]
                if sd.get("type") == "answer" and not negotiated:
                    negotiated = True
                    await peer.handle_answer(sd.get("sdp", ""))

                    def on_au(au, keyframe, pts, _p=peer):
                        _p.send_video_au(au, pts)

                    session.add_au_listener(on_au)
                    if (audio is not None
                            and getattr(audio, "format", "") == "opus"):
                        def on_audio(pts, packet, _p=peer):
                            _p.send_audio(packet, pts)

                        audio.add_listener(on_audio)
                    if hasattr(session, "request_keyframe"):
                        peer.on_ready = session.request_keyframe
            elif "ice" in data and peer is not None:
                cand = data["ice"] or {}
                line = cand.get("candidate", "") if isinstance(
                    cand, dict) else ""
                parts = line.split()
                if len(parts) >= 5:
                    await peer.add_remote_candidate_ip(parts[4])
    finally:
        teardown_peer()
    return ws


def register_selkies_routes(app: web.Application, cfg, session,
                            audio) -> None:
    """Mount the shim at /signalling and /{app}/signalling (both with
    and without trailing slash — the stock client builds the URL from
    its app name)."""
    from .turn import server_turn_config

    async def handler(request: web.Request):
        sockname = (request.transport.get_extra_info("sockname")
                    if request.transport is not None else None)
        advertise_ip = sockname[0] if sockname else "127.0.0.1"
        return await _signalling_handler(
            request, session, audio, server_turn_config(cfg),
            advertise_ip)

    app.router.add_get("/signalling", handler)
    app.router.add_get("/signalling/", handler)
    app.router.add_get("/{app_name}/signalling", handler)
    app.router.add_get("/{app_name}/signalling/", handler)
