"""Chaos-mode loopback bench: every registered fault point, recovered.

``bench.py --chaos`` drives the REAL serving path (SyntheticSource ->
StreamSession -> muxer -> aiohttp server, the same stack the loopback
serving-budget bench uses) and then injects every canonical failure
point from :mod:`..resilience.faults`, asserting per fault that the
session survives and the stream resumes (keyframe-bearing fragment
delivered after the last injected firing) within a bounded recovery
time.  Serving-path faults are injected against the live session;
``turn_refresh_401`` runs against a TURN allocation on a scripted
in-process responder (no coturn on the wire), and
``peer_rtcp_loss_burst`` plus the sustained-budget-breach scenario
drive the live :class:`..resilience.degrade.DegradeController` ladder
— downshift under breach, restore after, transitions visible on
``/metrics``.

The report is the ``chaos`` block bench emits: per-fault
``{fired, recovered, recovery_ms}`` plus the degradation scenario's
level trajectory.  The data-channel scenarios (ISSUE 11) ride a
packet-level SCTP loopback: ``sctp_drop_burst`` swallows packets
mid-typing and asserts retransmission redelivers every keystroke in
order to the X input backend; ``dcep_open_stall`` delays the
DATA_CHANNEL_ACK and asserts the deferred flush completes the open.

The RTCP feedback scenarios (ISSUE 14) ride the seeded impairment
shim (web/impair) against the real packet machinery
(webrtc/feedback): ``rtp_loss_burst`` tail-drops 4 media packets
mid-stream and asserts NACK/RTX repairs them with contiguous frames
and NO keyframe spent; ``pli_storm`` asserts the session's
rate-limited ``request_idr`` collapses a burst of PLIs into exactly
one granted IDR; the ``remb_cap`` scenario caps the link's bandwidth
and asserts the ladder walks down on the REMB headroom signal alone
and restores when the cap lifts.

The quality-plane scenario (ISSUE 17) parks the content plane's PSNR
floor above any achievable fidelity and asserts the resulting
``psnr_floor_breach`` event reaches ``/debug/events`` and that the
flight recorder's triggered dump embeds the content-state block.

Session-continuity scenarios (ISSUE 4) ride the same harness:
``device_preempt`` preempts the device mid-GOP and asserts the session
recovers on a restored device with the SAME SSRC, contiguous RTP
sequence numbers (observed through a peer-equivalent RTP tap on the AU
listener path — the exact packetizer state a live WebRTC peer carries
across recovery) and a bounded frame gap; ``mesh_chip_lost`` drops one
chip of a live multi-session mesh and asserts the survivors re-bucket
and every session resumes from its recovery IDR.

The rolling-restart scenario (ISSUE 19) retires a whole process
generation: a drain on the predecessor MIGRATES (encoder lineage +
per-connection wire continuity spooled through ``DNGD_HANDOFF_DIR``),
the successor adopts the snapshot before its first frame, and the
client redeems its resume token seeing the same SSRC, contiguous RTP
sequence numbers, exactly one recovery IDR and zero sheds — the
acceptance contract for zero-downtime restarts.
"""

from __future__ import annotations

import asyncio
import json
import logging
import struct
import time
from typing import Optional

from ..resilience import faults as rfaults
from ..resilience.degrade import DegradeController, SessionExecutor
from ..utils.config import Config
from .loopback import serving_budget_config

log = logging.getLogger(__name__)

__all__ = ["run_chaos"]


async def _await_frag(frags, after_t: float, deadline_s: float,
                      require_key: bool = False) -> Optional[float]:
    """Wait until the in-process sink logged a (keyframe-bearing, when
    ``require_key``) fragment newer than ``after_t``; returns its
    timestamp or None on timeout."""
    deadline = time.perf_counter() + deadline_s
    while time.perf_counter() < deadline:
        for t, key in reversed(frags):
            if t > after_t and (key or not require_key):
                return t
        await asyncio.sleep(0.05)
    return None


async def _drain_sink(queue, frags) -> None:
    """Consume an in-process subscriber queue, logging (t, keyframe)
    per media fragment — the production fan-out path, minus a socket."""
    try:
        while True:
            item = await queue.get()
            if item[0] == "frag":
                frags.append((time.perf_counter(),
                              bool(len(item) > 2 and item[2])))
    except asyncio.CancelledError:
        pass


# -- component harness: TURN refresh failure -> re-allocation ------------

class _ScriptedTurnWire:
    """In-process TURN responder: answers Allocate/Refresh/
    CreatePermission success so the allocation client's recovery path
    (refresh 401 via the fault point -> bounded re-allocate) runs
    without a TURN server on the wire."""

    def __init__(self, alloc):
        from ..webrtc import stun

        self.stun = stun
        self.alloc = alloc
        self.allocates = 0

    # asyncio.DatagramTransport surface the client uses
    def sendto(self, wire, addr=None):
        stun = self.stun
        try:
            req = stun.StunMessage.decode(wire)
        except ValueError:
            return
        if req.mtype == stun.ALLOCATE_REQUEST:
            self.allocates += 1
            resp = stun.StunMessage(stun.ALLOCATE_SUCCESS, txid=req.txid)
            resp.add_xor_address(stun.ATTR_XOR_RELAYED_ADDRESS,
                                 "203.0.113.7", 40000 + self.allocates)
            resp.add_xor_address(stun.ATTR_XOR_MAPPED_ADDRESS,
                                 "198.51.100.1", 50000)
            resp.attrs[stun.ATTR_LIFETIME] = struct.pack(">I", 600)
        elif req.mtype == stun.REFRESH_REQUEST:
            resp = stun.StunMessage(stun.REFRESH_SUCCESS, txid=req.txid)
            resp.attrs[stun.ATTR_LIFETIME] = struct.pack(">I", 600)
        elif req.mtype == stun.CREATE_PERMISSION_REQUEST:
            resp = stun.StunMessage(stun.CREATE_PERMISSION_SUCCESS,
                                    txid=req.txid)
        else:
            return
        self.alloc.datagram_received(resp.encode(), ("turn.test", 3478))

    def close(self):
        pass


async def _turn_refresh_scenario() -> dict:
    """turn_refresh_401: refresh rejected -> log-once -> bounded
    re-allocate restores the relay."""
    from ..webrtc.turn_client import TurnAllocation

    alloc = TurnAllocation(("turn.test", 3478), "user", "pass")
    wire = _ScriptedTurnWire(alloc)
    alloc._transport = wire           # skip the real UDP bind
    try:
        await alloc._do_allocate()
        first_relay = alloc.relayed_addr
        await alloc.create_permission("198.51.100.2")
        rfaults.arm("turn_refresh_401", count=1)
        t0 = time.perf_counter()
        ok = await alloc._refresh_once()
        recovery_ms = (time.perf_counter() - t0) * 1e3
        recovered = (ok and alloc.relayed_addr is not None
                     and alloc.relayed_addr != first_relay
                     and wire.allocates >= 2
                     and "198.51.100.2" in alloc._permissions)
        return {"fired": 1, "recovered": bool(recovered),
                "recovery_ms": round(recovery_ms, 1)}
    finally:
        alloc._transport = None       # the scripted wire has no socket
        alloc._closed = True


# -- component harness: SCTP data-channel input under packet loss --------

def _sctp_loop_pair(wire, rto_initial: float = 0.1,
                    rto_min: float = 0.05):
    """A client/server association pair wired through one deque — the
    packet-level loopback every SCTP scenario runs on (the association
    is transport-agnostic; DTLS is exercised by the CI stock-client
    smoke, which needs libssl)."""
    from ..webrtc.sctp import SctpAssociation

    server = SctpAssociation(role="server",
                             on_transmit=lambda p: wire.append(("c", p)),
                             rto_initial=rto_initial, rto_min=rto_min)
    client = SctpAssociation(role="client",
                             on_transmit=lambda p: wire.append(("s", p)),
                             rto_initial=rto_initial, rto_min=rto_min)

    def pump():
        while wire:
            dst, pkt = wire.popleft()
            (client if dst == "c" else server).receive(pkt)

    return client, server, pump


async def _sctp_input_scenario(recovery_budget_s: float) -> dict:
    """sctp_drop_burst: a scripted stock-selkies double types over the
    ``input`` data channel while the fault swallows outbound packets
    mid-burst.  Every keystroke must land at the X input backend, in
    order, redelivered by retransmission (the harness only polls the
    timers) — the ISSUE 11 acceptance run."""
    import types
    from collections import deque

    from ..webrtc.datachannel import DataChannelEndpoint
    from .input import FakeBackend, Injector
    from .selkies_shim import attach_input_channels

    loop = asyncio.get_running_loop()
    wire: deque = deque()
    client, server, pump = _sctp_loop_pair(wire)
    backend = FakeBackend()
    injector = Injector(backend)
    session = types.SimpleNamespace(stats_summary=lambda: {})
    peer = types.SimpleNamespace(on_datachannel=None, close_hooks=[])
    attach_input_channels(peer, session, injector, loop=loop)
    DataChannelEndpoint(server, dtls_role="server",
                        on_channel=peer.on_datachannel)
    client_dc = DataChannelEndpoint(client, dtls_role="client")
    client.connect()
    pump()
    ch = client_dc.open("input")
    pump()

    fired_before = rfaults.points()["sctp_drop_burst"].fired
    expect = []
    t0 = time.perf_counter()
    keysyms = list(range(97, 117))           # 20 keys = 40 events
    for i, ks in enumerate(keysyms):
        if i == len(keysyms) // 2:           # mid-typing, as specified
            rfaults.arm("sctp_drop_burst", count=4)
        ch.send(f"k,{ks},1")
        ch.send(f"k,{ks},0")
        expect += [("key", ks, True), ("key", ks, False)]
        pump()
        await asyncio.sleep(0)               # let the input worker run
    deadline = time.perf_counter() + recovery_budget_s
    while (len(backend.events) < len(expect)
           and time.perf_counter() < deadline):
        client.poll_timeout()
        server.poll_timeout()
        pump()
        await asyncio.sleep(0.02)
    await asyncio.sleep(0.05)                # drain the worker's tail
    fired = rfaults.points()["sctp_drop_burst"].fired - fired_before
    rfaults.disarm("sctp_drop_burst")
    retransmits = client.retransmits + server.retransmits
    ordered_ok = backend.events == expect
    for hook in peer.close_hooks:
        hook()
    client.close()
    server.close()
    return {
        "fired": fired,
        # the acceptance bar: every event delivered IN ORDER, the burst
        # really fired, and recovery came from retransmission
        # (dngd_sctp_retransmits_total > 0)
        "recovered": bool(ordered_ok and fired > 0 and retransmits > 0),
        "recovery_ms": round((time.perf_counter() - t0) * 1e3, 1),
        "retransmits": retransmits,
        "events_delivered": len(backend.events),
        "events_expected": len(expect),
    }


async def _dcep_stall_scenario(recovery_budget_s: float) -> dict:
    """dcep_open_stall: the DATA_CHANNEL_ACK for an inbound OPEN is
    delayed; the deferred flush must complete the open and the channel
    must then carry data."""
    from collections import deque

    from ..webrtc.datachannel import DataChannelEndpoint

    wire: deque = deque()
    client, server, pump = _sctp_loop_pair(wire)
    server_dc = DataChannelEndpoint(server, dtls_role="server")
    client_dc = DataChannelEndpoint(client, dtls_role="client")
    client.connect()
    pump()
    rfaults.arm("dcep_open_stall", count=1, delay_ms=150)
    t0 = time.perf_counter()
    ch = client_dc.open("input")
    pump()
    stalled = ch.state == "opening"          # the ACK really deferred
    fired = 1 - rfaults.armed_count("dcep_open_stall")
    deadline = time.perf_counter() + recovery_budget_s
    while ch.state != "open" and time.perf_counter() < deadline:
        server_dc.poll()
        client.poll_timeout()
        server.poll_timeout()
        pump()
        await asyncio.sleep(0.02)
    rfaults.disarm("dcep_open_stall")
    got = []
    srv_ch = server_dc.channels.get(ch.stream_id)
    if srv_ch is not None:
        srv_ch.on_message = got.append
    ch.send("k,97,1")
    pump()
    recovered = bool(stalled and ch.state == "open" and got == ["k,97,1"])
    client.close()
    server.close()
    return {"fired": fired, "recovered": recovered,
            "recovery_ms": round((time.perf_counter() - t0) * 1e3, 1)}


# -- RTCP feedback plane: loss repair, congestion, PLI storms ------------

async def _rtp_loss_scenario(recovery_budget_s: float) -> dict:
    """rtp_loss_burst: a 4-packet burst is tail-dropped mid-stream by
    the seeded impairment shim; the receiver NACKs the holes, the
    send-history ring answers with RTX retransmissions, and every frame
    arrives contiguous at the sink — with NO keyframe spent (repair
    happens *below* the quality ladder)."""
    from ..webrtc import rtcp as wrtcp
    from ..webrtc.feedback import FeedbackPlane, FeedbackSink, Pacer
    from ..webrtc.rtp import RtpStream
    from .impair import ImpairedLink

    sink_box: list = []
    link = ImpairedLink(lambda p: sink_box[0].on_rtp(p), seed=14,
                        jitter_ms=2.0, reorder=0.05)
    stream = RtpStream(96)
    pacer = Pacer(link.send)
    plane = FeedbackPlane(stream, link.send, pacer=pacer)
    plane.nack_enabled = True
    plane.enable_rtx(97)
    idr_requests: list = []
    plane.on_keyframe_request = idr_requests.append

    def on_rtcp(pkt: bytes) -> None:
        # receiver -> sender feedback path (lossless uplink, like RTCP
        # over the healthy reverse direction)
        for p in wrtcp.parse_compound(pkt):
            if p.get("nack_seqs"):
                plane.on_nack(p["nack_seqs"])

    sink = FeedbackSink(on_rtcp, stream.ssrc, rtx_ssrc=plane.rtx.ssrc)
    sink_box.append(sink)

    n_frames = 40
    fired_before = rfaults.points()["rtp_loss_burst"].fired
    t0 = time.perf_counter()
    for f in range(n_frames):
        if f == n_frames // 2:      # mid-stream, as specified
            rfaults.arm("rtp_loss_burst", count=1, packets=4)
        plane.send_frame([b"\x65" + b"\x00" * 1099] * 8, f * 3000)
        link.pump()
        sink.poll()
        await asyncio.sleep(0.01)
        link.pump()
        sink.poll()
    # drain: retransmissions + jittered stragglers
    deadline = time.perf_counter() + recovery_budget_s
    while ((sink.missing() or link.pending()
            or sink.frames + sink.frame_gaps < n_frames)
           and time.perf_counter() < deadline):
        link.pump()
        sink.poll()
        await asyncio.sleep(0.01)
    fired = rfaults.points()["rtp_loss_burst"].fired - fired_before
    rfaults.disarm("rtp_loss_burst")
    pacer.close()
    link.close()
    recovered = bool(
        fired == 1
        and plane.retransmits >= 1          # NACK-driven repair
        and sink.frames == n_frames         # contiguous at the sink
        and sink.frame_gaps == 0            # zero frame gaps
        and len(idr_requests) == 0)         # and NO IDR spent
    return {
        "fired": fired, "recovered": recovered,
        "recovery_ms": round((time.perf_counter() - t0) * 1e3, 1),
        "retransmits": plane.retransmits,
        "frames_delivered": sink.frames,
        "frame_gaps": sink.frame_gaps,
        "idr_requests": len(idr_requests),
        "nacks": sink.nacks_sent,
        "link": link.stats(),
    }


async def _remb_cap_scenario(cfg, session,
                             recovery_budget_s: float) -> dict:
    """Sustained bandwidth cap: the receiver's REMB converges on the
    cap, the headroom gauge drops below the congestion threshold, and
    the ladder walks DOWN on the forward signal alone (the latency
    budget is parked out of reach); lifting the cap restores."""
    from ..webrtc.feedback import FeedbackPlane, FeedbackSink, Pacer
    from ..webrtc.rtp import RtpStream
    from .impair import ImpairedLink

    sink_box: list = []
    # ~300 kbps bottleneck vs ~1.7 Mbps offered media
    link = ImpairedLink(lambda p: sink_box[0].on_rtp(p), seed=15,
                        bandwidth_bps=300_000.0)
    stream = RtpStream(96)
    pacer = Pacer(link.send)
    plane = FeedbackPlane(stream, link.send, pacer=pacer)

    def on_rtcp(pkt: bytes) -> None:
        from ..webrtc import rtcp as wrtcp

        for p in wrtcp.parse_compound(pkt):
            if "remb" in p:
                plane.on_remb(p["remb"]["bitrate_bps"],
                              p["remb"]["ssrcs"])

    # NACK disabled (interval parked): this scenario isolates the
    # congestion signal; the loss-repair loop is scenario rtp_loss_burst
    sink = FeedbackSink(on_rtcp, stream.ssrc,
                        nack_interval_s=1e9, give_up_s=0.2)
    sink_box.append(sink)

    ctl = DegradeController(
        SessionExecutor(session, cfg=cfg),
        budget_ms=1e9,                 # only REMB may move the ladder
        window=60, min_frames=8, breach_ticks=2, recover_ticks=3,
        cooldown_s=0.1, max_level=2)
    out: dict = {"ladder": [s.name for s in ctl.steps]}

    async def media_until(pred, budget_s: float) -> bool:
        deadline = time.perf_counter() + budget_s
        f = 0
        while time.perf_counter() < deadline:
            plane.send_frame([b"\x41" * 1100] * 6, f * 3000)
            f += 1
            link.pump()
            sink.poll(remb=True)
            ctl.tick()
            if pred():
                return True
            await asyncio.sleep(1 / 30)
            link.pump()
        return False

    t0 = time.perf_counter()
    try:
        engaged = await media_until(lambda: ctl.level >= 2,
                                    recovery_budget_s * 2)
        out["engaged"] = engaged
        out["capped_headroom"] = ctl.snapshot()["remb_headroom"]
        link.set_bandwidth(None)       # bottleneck lifted
        restored = await media_until(lambda: ctl.level == 0,
                                     recovery_budget_s * 2)
        out["restored_headroom"] = ctl.snapshot()["remb_headroom"]
        out["recovered"] = bool(engaged and restored)
        out["transitions"] = ctl.transitions
        out["recovery_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    finally:
        ctl.stop()
        plane.close()                  # retire the REMB series so the
        pacer.close()                  # later scenarios read None
        link.close()
        session.set_qp_offset(0)
        session.set_fps_cap(None)
    return out


async def _pli_storm_scenario(session,
                              recovery_budget_s: float) -> dict:
    """pli_storm: one RTCP arrival dispatches a burst of PLIs; the
    session's rate-limited ``request_idr`` must grant EXACTLY ONE
    keyframe inside the rate window (the rest collapse into a single
    deferred grant after it)."""
    from ..webrtc import rtcp as wrtcp

    monitor = wrtcp.PeerRtcpMonitor({0xFEED: ("video", 90_000)})
    granted: list = []

    def on_pli(kind: str, source: str) -> None:
        if session.request_idr(source):
            granted.append(source)

    monitor.on_pli = on_pli
    # let the rate window reopen ORGANICALLY (any earlier scenario's
    # grant + a possible deferred grant both age out) — no reaching
    # into the session's limiter internals, so the scenario works
    # against any session type carrying the request_idr contract
    await asyncio.sleep(2 * session.IDR_MIN_INTERVAL_S + 0.3)
    plis = 10
    rfaults.arm("pli_storm", count=1, plis=plis)
    t0 = time.perf_counter()
    # the storm rides an otherwise-ordinary RTCP arrival
    monitor.ingest(wrtcp.receiver_report(0x1, []))
    fired = 1 - rfaults.armed_count("pli_storm")
    rfaults.disarm("pli_storm")
    # the 9 over-limit requests must have collapsed into one pending
    # deferred grant (observable via the public contract: a fresh
    # request inside the window is NOT granted)
    deferred_window = session.request_idr("pli") is False
    monitor.close()
    recovered = bool(fired == 1 and len(granted) == 1
                     and deferred_window)
    return {
        "fired": fired, "recovered": recovered,
        "recovery_ms": round((time.perf_counter() - t0) * 1e3, 1),
        "plis": plis,
        "idr_granted_in_window": len(granted),
        "window_still_closed": deferred_window,
    }


# -- quality plane: forced PSNR-floor breach -> event + flight dump ------

async def _content_breach_scenario(session, port,
                                   recovery_budget_s: float) -> dict:
    """Park the quality plane's PSNR floor above any achievable
    fidelity (DNGD_CONTENT_PSNR_FLOOR=99); the in-graph PSNR of the
    very next sampled frame sits below it, so a ``psnr_floor_breach``
    event must land on the fleet timeline (visible at /debug/events)
    and the flight recorder's triggered dump must embed the content
    state block — the ISSUE 17 observability acceptance run.  The floor
    is restored afterwards, so later scenarios see the real config."""
    import os

    import aiohttp

    from ..obs import events as obse
    from ..obs import flight as obsf

    def breach_count() -> int:
        return sum(1 for e in obse.EVENTS.recent(1024)
                   if e.get("kind") == "psnr_floor_breach")

    before = breach_count()
    old = os.environ.get("DNGD_CONTENT_PSNR_FLOOR")
    os.environ["DNGD_CONTENT_PSNR_FLOOR"] = "99"
    t0 = time.perf_counter()
    try:
        deadline = time.perf_counter() + recovery_budget_s
        while (breach_count() == before
               and time.perf_counter() < deadline):
            await asyncio.sleep(0.05)
    finally:
        if old is None:
            os.environ.pop("DNGD_CONTENT_PSNR_FLOOR", None)
        else:
            os.environ["DNGD_CONTENT_PSNR_FLOOR"] = old
    emitted = breach_count() - before
    # the event must be CLIENT-visible, not just in-process
    async with aiohttp.ClientSession() as http:
        async with http.get(
                f"http://127.0.0.1:{port}/debug/events") as resp:
            events_text = await resp.text()
    visible = "psnr_floor_breach" in events_text
    dump = obsf.FLIGHT.find_dump("psnr_floor_breach")
    content = (dump or {}).get("content") or {}
    dump_ok = bool(dump and content.get("sessions"))
    return {
        "fired": emitted,
        "recovered": bool(emitted >= 1 and visible and dump_ok),
        "recovery_ms": round((time.perf_counter() - t0) * 1e3, 1),
        "event_visible": visible,
        "flight_dump": bool(dump),
        "flight_content_block": dump_ok,
    }


# -- damage plane: calm -> full-frame spike -> event, charge, no shed ----

async def _damage_spike_scenario(session, port, frags,
                                 recovery_budget_s: float) -> dict:
    """A calm desktop jumping to a full-frame change (ISSUE 20): the
    departure must surface as a ``damage_spike`` timeline event
    (client-visible at /debug/events) with a flight dump carrying the
    content block, the capacity charge must ride to full cost
    (placement priced the spike headroom in advance), and the serving
    co-tenant must keep streaming — a spike engages the backpressure
    ladder, never the shed list.  The spike is driven through the
    content plane's real record path under a scenario session id (the
    loopback's own content mix is not steerable from here), so
    emission, the calm-history rule, debounce, charge, and the dump
    trigger all exercise production code."""
    import aiohttp

    from ..obs import content as obsc
    from ..obs import events as obse
    from ..obs import flight as obsf

    sid = "chaos-damage-spike"
    plane = obsc.PLANE

    def spike_count() -> int:
        return sum(1 for e in obse.EVENTS.recent(1024)
                   if e.get("kind") == "damage_spike")

    before = spike_count()
    t0 = time.perf_counter()
    calm_charge = spike_charge = None
    try:
        # 31 calm frames: the spike rule requires calm history to
        # depart from (median of the prior window <= thr/2)
        for _ in range(31):
            plane.record(sid, {"damage_fraction": 0.02})
        calm_charge = plane.damage_charge(sid)
        plane.record(sid, {"damage_fraction": 1.0})      # the spike
        spike_charge = plane.damage_charge(sid)
        deadline = time.perf_counter() + recovery_budget_s
        while (spike_count() == before
               and time.perf_counter() < deadline):
            await asyncio.sleep(0.05)
        emitted = spike_count() - before
        async with aiohttp.ClientSession() as http:
            async with http.get(
                    f"http://127.0.0.1:{port}/debug/events") as resp:
                events_text = await resp.text()
        visible = "damage_spike" in events_text
        dump = obsf.FLIGHT.find_dump("damage_spike")
        dump_ok = bool(dump
                       and (dump.get("content") or {}).get("sessions"))
        # the REAL serving session must still be delivering media
        flow = await _await_frag(frags, t0, recovery_budget_s)
    finally:
        plane.drop(sid)
    charged = (calm_charge is not None and calm_charge < 0.5
               and spike_charge is not None and spike_charge >= 0.99)
    return {
        "fired": emitted,
        "recovered": bool(emitted >= 1 and visible and dump_ok
                          and charged and flow is not None),
        "recovery_ms": round((time.perf_counter() - t0) * 1e3, 1),
        "event_visible": visible,
        "flight_dump": bool(dump),
        "flight_content_block": dump_ok,
        "calm_charge": calm_charge,
        "spike_charge": spike_charge,
        "cotenant_flow": flow is not None,
    }


# -- continuity: device preemption with SSRC/seq lineage assertions ------

class _RtpTap:
    """Peer-equivalent RTP packetizer riding the AU-listener path.

    A live WebRTC peer holds one :class:`..webrtc.rtp.RtpStream` whose
    SSRC and sequence counter persist for the peer's lifetime; device
    recovery swaps the ENCODER but never the peer, so continuity on the
    wire follows from this object surviving.  The tap IS that object —
    it packetizes every delivered AU exactly like the peer's video
    track and records what hit the (virtual) wire, so the bench asserts
    the client-visible contract: one SSRC, contiguous sequence numbers,
    a bounded AU gap across recovery, and a keyframe first after it."""

    def __init__(self, codec_name: str):
        from ..webrtc.rtp import RtpStream

        self.codec = codec_name
        self.stream = RtpStream(96)
        self.ssrcs = set()
        self.seqs: list = []
        self.aus: list = []            # (t, keyframe)

    def on_au(self, au: bytes, keyframe: bool, pts: int) -> None:
        from ..webrtc.rtp import packetize_h264, packetize_vp8, parse_header
        from .mp4 import split_annexb

        if self.codec.startswith("h264"):
            payloads = packetize_h264(split_annexb(au))
        elif self.codec.startswith("vp8"):
            payloads = packetize_vp8(au)
        else:
            payloads = [au]
        for pkt in self.stream.packetize(payloads, pts & 0xFFFFFFFF):
            hdr = parse_header(pkt)
            self.ssrcs.add(hdr["ssrc"])
            self.seqs.append(hdr["seq"])
        self.aus.append((time.perf_counter(), bool(keyframe)))

    def seq_contiguous(self) -> bool:
        return all((b - a) & 0xFFFF == 1
                   for a, b in zip(self.seqs, self.seqs[1:]))

    async def await_au(self, after_t: float, deadline_s: float,
                       require_key: bool = False) -> Optional[float]:
        deadline = time.perf_counter() + deadline_s
        while time.perf_counter() < deadline:
            for t, key in reversed(self.aus):
                if t > after_t and (key or not require_key):
                    return t
            await asyncio.sleep(0.05)
        return None


async def _device_preempt_scenario(session, recovery_budget_s: float
                                   ) -> dict:
    """Preempt the device mid-GOP; the session must re-acquire, restore
    the encoder-state checkpoint and resume THE SAME stream lineage."""
    tap = _RtpTap(session.codec_name)
    session.add_au_listener(tap.on_au)
    try:
        if await tap.await_au(0.0, recovery_budget_s) is None:
            return {"fired": 0, "recovered": False,
                    "error": "no AU before injection"}
        pre_recoveries = session._recoveries
        muxer_before = session.muxer          # hold the OBJECT: an id()
        # compare could false-pass on address reuse after a rebuild
        last_before = tap.aus[-1][0]
        rfaults.arm("device_preempt", count=1)
        t0 = time.perf_counter()
        while (rfaults.armed_count("device_preempt")
               and time.perf_counter() - t0 < recovery_budget_s):
            await asyncio.sleep(0.05)
        t_fired = time.perf_counter()         # pre-arm pipelined AUs
        fired = 1 - rfaults.armed_count("device_preempt")
        rfaults.disarm("device_preempt")
        # the recovery must COMPLETE (counter increments) before any
        # keyframe can be the recovery IDR — a scheduled GOP keyframe
        # landing between arm and fire must not satisfy the wait
        deadline = time.perf_counter() + recovery_budget_s
        while (session._recoveries == pre_recoveries
               and time.perf_counter() < deadline):
            await asyncio.sleep(0.05)
        t_rec = (await tap.await_au(t_fired, recovery_budget_s,
                                    require_key=True)
                 if session._recoveries > pre_recoveries else None)
        alive = session._thread is not None and session._thread.is_alive()
        gap_ms = (None if t_rec is None
                  else round((t_rec - last_before) * 1e3, 1))
        gap_bounded = (gap_ms is not None
                       and gap_ms <= recovery_budget_s * 1e3)
        ckpt_restored = session._ckpt.state is not None
        # the verdict carries EVERY acceptance clause (bounded frame
        # gap, checkpoint actually restored) so a standalone bench run
        # exits non-zero on a regression — not just the CI assertions
        recovered = bool(
            fired == 1 and t_rec is not None and alive
            and session._recoveries == pre_recoveries + 1
            and len(tap.ssrcs) == 1           # same SSRC across recovery
            and tap.seq_contiguous()          # no RTP sequence break
            and session.muxer is muxer_before  # timestamp lineage
            and gap_bounded and ckpt_restored)
        return {
            "fired": fired, "recovered": recovered,
            "recovery_ms": (None if t_rec is None
                            else round((t_rec - t0) * 1e3, 1)),
            "frame_gap_ms": gap_ms,
            "frame_gap_bounded": gap_bounded,
            "ssrc_count": len(tap.ssrcs),
            "seq_contiguous": tap.seq_contiguous(),
            "recoveries": session._recoveries,
            "checkpoint_restored": ckpt_restored,
        }
    finally:
        rfaults.disarm("device_preempt")
        session.remove_au_listener(tap.on_au)


# -- continuity: mesh chip loss -> N->N-1 re-bucket ----------------------

async def _mesh_failover_scenario(quick: bool,
                                  recovery_budget_s: float,
                                  timeout_s: float) -> dict:
    """Drop one chip of a live multi-session mesh mid-GOP; surviving
    chips re-bucket and every session resumes from its recovery IDR.
    Needs >= 2 devices (CI forces host-platform devices; a single
    tunnel-attached chip reports skipped)."""
    import jax

    ndev = len(jax.devices())
    if ndev < 2:
        return {"skipped": f"{ndev} device(s); elastic failover needs "
                           ">= 2", "recovered": None}
    from .multisession import BatchStreamManager

    n_sessions = min(ndev, 8)
    # full mode runs the acceptance geometry (8x1080p -> 7 chips);
    # quick keeps CI on a compile-friendly bucket
    w, h = (128, 96) if quick else (1920, 1080)
    cfg = serving_budget_config(w, h, 30, extra={
        "TPU_SESSIONS": str(n_sessions),
        "TPU_MESH": str(n_sessions),
        "ENCODER_GOP": "30",
        "WEBRTC_ENABLE_RESIZE": "true",
    })
    loop = asyncio.get_running_loop()
    from ..rfb.source import SyntheticSource
    sources = [SyntheticSource(w, h, fps=float(cfg.refresh))
               for _ in range(n_sessions)]
    mgr = BatchStreamManager(cfg, sources, loop=loop)
    mgr.start()
    sinks = [mgr.session(i).subscribe() for i in range(n_sessions)]
    frag_logs: list = [[] for _ in range(n_sessions)]
    drains = [asyncio.ensure_future(_drain_sink(q, f))
              for q, f in zip(sinks, frag_logs)]
    try:
        # warm up: a keyframe on every hub proves the compiled IDR step
        for frags in frag_logs:
            if await _await_frag(frags, 0.0, timeout_s,
                                 require_key=True) is None:
                return {"fired": 0, "recovered": False,
                        "error": "no first frame before chip loss"}
        # ... and a SECOND keyframe on hub 0 proves a full GOP of P
        # ticks ran, i.e. the P-step compile is behind us — otherwise
        # that compile stalls the loop across the fault-consumption
        # window below and the injection looks like it never fired
        if await _await_frag(frag_logs[0], time.perf_counter(),
                             timeout_s, require_key=True) is None:
            return {"fired": 0, "recovered": False,
                    "error": "no second GOP before chip loss"}
        mesh_before = list(mgr.mesh.devices.shape)
        rfaults.arm("mesh_chip_lost", count=1)
        t0 = time.perf_counter()
        while (rfaults.armed_count("mesh_chip_lost")
               and time.perf_counter() - t0 < timeout_s):
            await asyncio.sleep(0.05)
        fired = 1 - rfaults.armed_count("mesh_chip_lost")
        rfaults.disarm("mesh_chip_lost")
        # every surviving session must deliver its recovery IDR (the
        # rebuilt step recompiles, so the wait rides the full timeout)
        t_rebuilt = time.perf_counter()
        recovered_all = True
        for frags in frag_logs:
            if await _await_frag(frags, t_rebuilt, timeout_s,
                                 require_key=True) is None:
                recovered_all = False
                break
        alive = mgr._thread is not None and mgr._thread.is_alive()
        stats = mgr.stats_summary()
        return {
            "fired": fired,
            "recovered": bool(fired == 1 and recovered_all and alive
                              and mgr._rebuilds >= 1),
            "recovery_ms": round((time.perf_counter() - t0) * 1e3, 1),
            "sessions": n_sessions,
            "mesh_before": mesh_before,
            "mesh_after": list(mgr.mesh.devices.shape),
            "dead_chips": stats["dead_chips"],
            "geometry": stats["geometry"],
        }
    finally:
        rfaults.disarm("mesh_chip_lost")
        for d in drains:
            d.cancel()
        mgr.close()


# -- continuity: rolling restart -> drain-to-migrate handoff -------------

async def _rolling_restart_scenario(recovery_budget_s: float,
                                    timeout_s: float) -> dict:
    """Restart the serving process under live clients (ISSUE 19): the
    predecessor's drain MIGRATES — encoder lineage + wire continuity
    spool through DNGD_HANDOFF_DIR, the successor adopts them before
    its first frame, and the client resumes with its token seeing the
    SAME SSRC, contiguous RTP sequence numbers, exactly one recovery
    IDR and ZERO sheds.  A rolling restart must be a non-event on the
    wire.  The entry carries no ``fired`` key: a restart is not an
    rfaults injection point, so the per-fault flight accounting below
    skips it (like ``content_quality``)."""
    import shutil
    import tempfile

    import aiohttp

    from ..rfb.source import SyntheticSource
    from .server import bound_port, serve
    from .session import StreamSession

    tmpdir = tempfile.mkdtemp(prefix="dngd-handoff-")
    w, h = 128, 96
    cfg = serving_budget_config(w, h, 30, extra={
        "FLEET_ENABLE": "true",
        "DNGD_HANDOFF_DIR": tmpdir,
        # generous TTL: the successor's first compile must never race
        # the resume token out of its pending window on a loaded box
        "DNGD_HANDOFF_TOKEN_TTL_S": "600",
        # a LONG GOP isolates the recovery IDR: any keyframe the
        # successor emits inside the observation window is the resume
        # IDR, never a scheduled GOP boundary
        "ENCODER_GOP": "120",
        "DEGRADE_ENABLE": "false",
    })
    loop = asyncio.get_running_loop()
    out: dict = {"recovered": False}
    t0 = time.perf_counter()
    session_a = session_b = None
    runner_a = runner_b = None
    tap_a = tap_b = None
    try:
        # ---- generation A: live stream + one resumable client --------
        source_a = SyntheticSource(w, h, fps=float(cfg.refresh))
        session_a = StreamSession(cfg, source_a, loop=loop)
        tap_a = _RtpTap(session_a.codec_name)
        session_a.add_au_listener(tap_a.on_au)
        session_a.start()
        runner_a = await serve(cfg, session_a)
        port_a = bound_port(runner_a)
        hmgr_a = runner_a.app["handoff"]
        fleet_a = runner_a.app["fleet"]
        migrate_msg = None
        async with aiohttp.ClientSession() as http:
            async with http.ws_connect(f"http://127.0.0.1:{port_a}/ws",
                                       max_msg_size=0) as ws:
                hello = await ws.receive_json(timeout=timeout_s)
                token = hello.get("resume")
                out["token_issued"] = bool(token)
                if not token:
                    out["error"] = "no resume token in hello"
                    return out
                # the tap IS this client's wire state: the same video
                # RtpStream a live peer's export_wire would snapshot
                hmgr_a.attach_wire(
                    token,
                    lambda: {"video": tap_a.stream.export_state()})
                if await tap_a.await_au(0.0, timeout_s,
                                        require_key=True) is None:
                    out["error"] = "no keyframe before restart"
                    return out
                # drain-to-migrate: the preStop-hook path (SIGTERM
                # drives the same handoff_migrate coroutine)
                async with http.post(
                        f"http://127.0.0.1:{port_a}/debug/drain") as r:
                    body = await r.json()
                out["handoff"] = body.get("handoff")
                # the connected client must be handed its resume token
                deadline = time.perf_counter() + recovery_budget_s
                while time.perf_counter() < deadline:
                    msg = await ws.receive(timeout=max(
                        0.1, deadline - time.perf_counter()))
                    if msg.type == aiohttp.WSMsgType.TEXT:
                        data = json.loads(msg.data)
                        if data.get("type") == "migrate":
                            migrate_msg = data
                            break
                    elif msg.type in (aiohttp.WSMsgType.CLOSED,
                                      aiohttp.WSMsgType.CLOSE,
                                      aiohttp.WSMsgType.ERROR):
                        break
        out["migrate_notified"] = migrate_msg is not None
        if migrate_msg is None:
            out["error"] = "no migrate message before socket close"
            return out
        token = migrate_msg.get("resume") or token
        seq_a_last = tap_a.seqs[-1] if tap_a.seqs else None
        sheds_a = fleet_a.sheds if fleet_a is not None else 0
        # the predecessor process generation ends here
        session_a.remove_au_listener(tap_a.on_au)
        session_a.close()
        await runner_a.cleanup()
        runner_a = None

        # ---- generation B: adopt the spool, resume the client --------
        source_b = SyntheticSource(w, h, fps=float(cfg.refresh))
        session_b = StreamSession(cfg, source_b, loop=loop)
        # serve() consumes the spool BEFORE the session starts, so the
        # adoption is queued ahead of frame 0 and the successor's first
        # frame continues the predecessor's GOP (no fresh-start IDR)
        runner_b = await serve(cfg, session_b)
        port_b = bound_port(runner_b)
        hmgr_b = runner_b.app["handoff"]
        fleet_b = runner_b.app["fleet"]
        staged = dict(hmgr_b._pending.get(token) or {})
        wire = staged.get("wire") or {}
        out["wire_staged"] = bool(wire.get("video"))
        session_b.start()
        deadline = time.perf_counter() + timeout_s
        while (not session_b._handoff_adopted
               and time.perf_counter() < deadline):
            await asyncio.sleep(0.05)
        out["adopted"] = session_b._handoff_adopted
        # the successor-side tap seeds from the staged wire exactly as
        # _handle_offer seeds a resuming peer (peer.import_wire): the
        # sequence frontier crossed the process boundary in the spool
        tap_b = _RtpTap(session_b.codec_name)
        if wire.get("video"):
            tap_b.stream.import_state(wire["video"])
        session_b.add_au_listener(tap_b.on_au)
        # flush the tap-attach forced keyframe BEFORE reconnecting so
        # the exactly-one-IDR count below sees only the resume IDR
        await tap_b.await_au(0.0, recovery_budget_s, require_key=True)
        t_reconnect = time.perf_counter()
        hello_b = None
        async with aiohttp.ClientSession() as http:
            async with http.ws_connect(
                    f"http://127.0.0.1:{port_b}/ws?resume={token}",
                    max_msg_size=0) as ws2:
                hello_b = await ws2.receive_json(timeout=timeout_s)
                # the join-subscribe keyframe and request_idr("handoff")
                # must collapse into ONE recovery IDR on the wire
                t_idr = await tap_b.await_au(t_reconnect,
                                             recovery_budget_s,
                                             require_key=True)
                if t_idr is not None:
                    # settle: a second IDR inside the long GOP would be
                    # a resume-storm leak, not a scheduled keyframe
                    await asyncio.sleep(1.0)
        out["resumed"] = bool(hello_b and hello_b.get("resumed"))
        keys_after_resume = sum(1 for t, k in tap_b.aus
                                if k and t > t_reconnect)
        async with aiohttp.ClientSession() as http:
            async with http.get(
                    f"http://127.0.0.1:{port_b}/metrics") as resp:
                metrics_b = await resp.text()
        seq_boundary_ok = (
            seq_a_last is not None and bool(tap_b.seqs)
            and (tap_b.seqs[0] - seq_a_last) & 0xFFFF == 1)
        alive = (session_b._thread is not None
                 and session_b._thread.is_alive())
        sheds_b = fleet_b.sheds if fleet_b is not None else 0
        migs_b = fleet_b.migrations if fleet_b is not None else 0
        out.update({
            "migrated": int((out.get("handoff") or {})
                            .get("migrated") or 0),
            "ssrc_count": len(tap_a.ssrcs | tap_b.ssrcs),
            "seq_contiguous": (tap_a.seq_contiguous()
                               and tap_b.seq_contiguous()),
            "seq_boundary_contiguous": seq_boundary_ok,
            "recovery_idr": t_idr is not None,
            "idrs_after_resume": keys_after_resume,
            "sheds": sheds_a + sheds_b,
            "migrations_admitted": migs_b,
            "metrics_visible": (
                "dngd_handoff_sessions_total" in metrics_b
                and "dngd_handoff_resume_total" in metrics_b),
            "recovery_ms": round((time.perf_counter() - t0) * 1e3, 1),
        })
        out["recovered"] = bool(
            out["migrated"] >= 1 and out["adopted"]
            and out["wire_staged"] and out["resumed"]
            and t_idr is not None and keys_after_resume == 1
            and len(tap_a.ssrcs | tap_b.ssrcs) == 1  # same SSRC across
            and out["seq_contiguous"] and seq_boundary_ok
            and sheds_a == 0 and sheds_b == 0         # zero sheds
            and migs_b >= 1
            and out["metrics_visible"] and alive)
        return out
    finally:
        for sess, tap in ((session_a, tap_a), (session_b, tap_b)):
            if sess is not None and tap is not None:
                sess.remove_au_listener(tap.on_au)
        for sess in (session_a, session_b):
            if sess is not None:
                sess.close()
        for runner in (runner_a, runner_b):
            if runner is not None:
                await runner.cleanup()
        shutil.rmtree(tmpdir, ignore_errors=True)


# -- the chaos run -------------------------------------------------------

async def run_chaos(cfg: Optional[Config] = None,
                    width: int = 320, height: int = 240, fps: int = 30,
                    quick: bool = False,
                    recovery_budget_s: float = 30.0,
                    timeout_s: float = 600.0,
                    continuity: bool = True,
                    continuity_only: bool = False) -> dict:
    """Inject every canonical fault point; report per-fault recovery.

    ``continuity_only`` restricts the run to the session-continuity
    scenarios (``device_preempt`` + ``mesh_chip_lost``) — the CI
    continuity-smoke step; ``continuity=False`` skips them (the
    pre-existing chaos-smoke scope)."""
    from ..obs.budget import LEDGER
    from ..rfb.source import SyntheticSource
    from .server import bound_port, serve
    from .session import StreamSession

    if quick:
        width, height, fps = 128, 96, 30
    if cfg is None:
        cfg = serving_budget_config(width, height, fps, extra={
            "WEBRTC_ENABLE_RESIZE": "true",
            # a short checkpoint cadence so the preemption scenario
            # restores a real checkpoint, not the no-lineage fallback
            "DNGD_CKPT_INTERVAL": "1.0",
            # the scenarios drive their OWN fast-tick controller; the
            # server's 1 s-cadence one would fight it over the ladder
            "DEGRADE_ENABLE": "false"})
    rfaults.disarm_all()
    LEDGER.clear()
    # flight recorder: every injected fault must produce a postmortem
    # dump (counted per fault point, asserted in the report below)
    from ..obs import flight as obsf
    obsf.FLIGHT.clear()
    loop = asyncio.get_running_loop()
    source = SyntheticSource(cfg.sizew, cfg.sizeh, fps=float(cfg.refresh))
    session = StreamSession(cfg, source, loop=loop)
    session.start()
    runner = await serve(cfg, session)
    port = bound_port(runner)

    sink = session.subscribe()        # production fan-out, in-process sink
    frags: list = []
    drain = asyncio.ensure_future(_drain_sink(sink, frags))
    report: dict = {"mode": "chaos-loopback", "quick": quick,
                    "geometry": f"{cfg.sizew}x{cfg.sizeh}@{cfg.refresh}",
                    "faults": {}, "degrade": {}, "continuity": {}}
    t_start = time.perf_counter()

    async def serving_fault(name: str, count: int,
                            require_key: bool, **params) -> dict:
        t0 = time.perf_counter()
        rfaults.arm(name, count=count, **params)
        # wait until every armed firing was consumed (the fault actually
        # hit the path), then for the stream to resume past it
        while (rfaults.armed_count(name)
               and time.perf_counter() - t0 < recovery_budget_s):
            await asyncio.sleep(0.05)
        fired = count - rfaults.armed_count(name)
        rfaults.disarm(name)
        t_rec = await _await_frag(frags, time.perf_counter(),
                                  recovery_budget_s,
                                  require_key=require_key)
        alive = session._thread is not None and session._thread.is_alive()
        return {"fired": fired,
                "recovered": bool(t_rec is not None and alive
                                  and fired == count),
                "recovery_ms": (round((t_rec - t0) * 1e3, 1)
                                if t_rec is not None else None)}

    try:
        # warm up: the first keyframe proves compile + full path
        first = await _await_frag(frags, 0.0, timeout_s * 0.6,
                                  require_key=True)
        if first is None:
            raise RuntimeError("chaos: no first frame within budget")
        # Pre-compile the degraded-qp executables: the ladder's qp_up
        # step is one fresh jit specialization, and that compile must
        # land in WARMUP wall-clock, not inside a recovery budget (the
        # control loop under test is the ladder, not XLA).
        session.set_qp_offset(SessionExecutor.QP_STEP)
        session.request_keyframe()
        t = await _await_frag(frags, time.perf_counter(),
                              timeout_s * 0.3, require_key=True)
        if t is not None:                     # one P at the degraded qp
            await _await_frag(frags, t, 30.0)
        session.set_qp_offset(0)
        session.request_keyframe()
        await _await_frag(frags, time.perf_counter(), 30.0,
                          require_key=True)

        if not continuity_only:
            # 1) collect failure -> frame dropped, stale P suppressed,
            #    forced-IDR resync (recovery requires the IDR, not any
            #    frag)
            report["faults"]["collect_timeout"] = await serving_fault(
                "collect_timeout", count=2, require_key=True)

            # 2) submit failure -> frames dropped, breaker counts,
            #    session survives well under the open threshold
            report["faults"]["device_submit_error"] = await serving_fault(
                "device_submit_error", count=2, require_key=False)

            # 3) X server gone -> bounded retry until the source
            #    returns, then IDR resync
            report["faults"]["xserver_gone"] = await serving_fault(
                "xserver_gone", count=5, require_key=True)

            # 4) websocket send stall -> queue eviction then slow-
            #    subscriber eviction; the SESSION and the other
            #    (in-process) subscriber must be unaffected, and the
            #    evicted client can reconnect
            report["faults"]["ws_send_stall"] = await _ws_stall_scenario(
                cfg, session, port, frags, recovery_budget_s)

            # 5) TURN refresh failure -> bounded re-allocation
            #    (component harness on a scripted responder)
            report["faults"]["turn_refresh_401"] = \
                await _turn_refresh_scenario()

            # 5b) SCTP data-channel input: packet-loss burst mid-typing
            #     -> retransmission redelivers every keystroke in order
            #     (ISSUE 11 acceptance), and a stalled DCEP ACK still
            #     completes the channel open
            report["faults"]["sctp_drop_burst"] = \
                await _sctp_input_scenario(recovery_budget_s)
            report["faults"]["dcep_open_stall"] = \
                await _dcep_stall_scenario(recovery_budget_s)

            # 5c) RTCP feedback plane (ISSUE 14): a seeded loss burst
            #     repairs via NACK/RTX with contiguous frames and NO
            #     IDR; a PLI storm costs exactly one rate-limited IDR
            #     (the REMB bandwidth-cap scenario runs after 6, which
            #     rebuilds the whole degrade block)
            report["faults"]["rtp_loss_burst"] = \
                await _rtp_loss_scenario(recovery_budget_s)
            report["faults"]["pli_storm"] = \
                await _pli_storm_scenario(session, recovery_budget_s)

            # 5d) quality plane (ISSUE 17): a forced PSNR-floor breach
            #     must surface as a timeline event at /debug/events and
            #     a flight dump carrying the content-state block
            #     (separate report key: it is a telemetry trigger, not
            #     an rfaults injection point, so the per-fault flight
            #     accounting below must not expect a fault-fire dump)
            report["content_quality"] = await _content_breach_scenario(
                session, port, recovery_budget_s)

            # 5e) hostile-wire co-tenancy (ISSUE 18): a peer flooding
            #     spoofed acks + malformed JSON walks the ingress
            #     ladder to eviction (events + flight dump) while a
            #     legit co-tenant keeps streaming; component floods
            #     cover the NACK-amplification and malformed-SCTP
            #     vectors (separate report key like content_quality:
            #     not an rfaults injection point)
            report["hostile_client"] = await _hostile_client_scenario(
                session, port, frags, recovery_budget_s)

            # 5f) damage plane (ISSUE 20): a calm desktop spiking to a
            #     full-frame change must emit damage_spike (events +
            #     flight dump with the content block), ride the
            #     capacity charge to full cost, and never disturb the
            #     serving co-tenant (separate report key like
            #     content_quality: not an rfaults injection point)
            report["damage_spike"] = await _damage_spike_scenario(
                session, port, frags, recovery_budget_s)

            # 6) RTCP loss burst + sustained budget breach -> the
            #    degradation ladder engages, then restores
            report["degrade"] = await _degrade_scenario(
                cfg, session, recovery_budget_s)

            # 6b) sustained bandwidth cap -> REMB-driven ladder
            #     downshift and restore (the forward congestion signal)
            report["degrade"]["remb_cap"] = \
                await _remb_cap_scenario(cfg, session,
                                         recovery_budget_s)
            report["faults"]["peer_rtcp_loss_burst"] = {
                "fired": report["degrade"]["loss_burst"]["fired"],
                "recovered": report["degrade"]["loss_burst"]["recovered"],
                "recovery_ms":
                    report["degrade"]["loss_burst"]["recovery_ms"],
            }

        if continuity or continuity_only:
            # 7) device preemption mid-GOP -> checkpoint restore on a
            #    re-acquired device, same SSRC/seq/timestamp lineage
            report["continuity"]["device_preempt"] = \
                await _device_preempt_scenario(session, recovery_budget_s)

            # 8) mesh chip lost -> N->N-1 re-bucket, recovery IDR on
            #    every surviving session
            report["continuity"]["mesh_chip_lost"] = \
                await _mesh_failover_scenario(quick, recovery_budget_s,
                                              timeout_s * 0.5)

            # 9) rolling restart -> drain-to-migrate handoff (ISSUE 19):
            #    the successor adopts the spooled snapshot and the
            #    client resumes on the same SSRC with contiguous seq,
            #    exactly one recovery IDR and zero sheds (no "fired"
            #    key: not an rfaults injection point, so the per-fault
            #    flight accounting skips it)
            report["continuity"]["rolling_restart"] = \
                await _rolling_restart_scenario(recovery_budget_s,
                                                timeout_s * 0.5)

        # /metrics must carry the transitions (acceptance criterion)
        import aiohttp

        async with aiohttp.ClientSession() as http:
            async with http.get(
                    f"http://127.0.0.1:{port}/metrics") as resp:
                text = await resp.text()
        report["metrics_visible"] = (
            "dngd_fault_injections_total" in text
            and (continuity_only
                 or ("dngd_degrade_step" in text
                     and "dngd_degrade_transitions_total" in text
                     and "dngd_sctp_retransmits_total" in text
                     and "dngd_rtx_packets_total" in text
                     and "dngd_nack_received_total" in text
                     and "dngd_idr_requests_total" in text
                     and "dngd_content_psnr_db" in text
                     and "dngd_content_damage_fraction" in text
                     and "dngd_ingress_violations_total" in text
                     and "dngd_ingress_peers" in text))
            and (not (continuity or continuity_only)
                 or "dngd_session_recoveries_total" in text))
    finally:
        rfaults.disarm_all()
        drain.cancel()
        session.close()
        await runner.cleanup()

    report["wall_s"] = round(time.perf_counter() - t_start, 2)

    # -- flight-recorder assertions (ISSUE 13 acceptance) --------------
    # every fault point that actually FIRED must have produced at least
    # one dump, and the continuity faults' dumps must carry the
    # postmortem payload (journeys + the triggering event + the budget)
    obsf.FLIGHT.flush_spool()
    by_reason = obsf.FLIGHT.by_reason()
    fired_points = [k for k, v in report["faults"].items()
                    if v.get("fired")]
    fired_points += [k for k, v in report["continuity"].items()
                     if v.get("fired")]
    per_fault = {pt: by_reason.get(f"fault-fire:{pt}", 0)
                 for pt in fired_points}
    content_ok: dict = {}
    for pt in ("device_preempt", "mesh_chip_lost"):
        if report["continuity"].get(pt, {}).get("fired"):
            dump = obsf.FLIGHT.find_dump("fault-fire", pt)
            content_ok[pt] = bool(
                dump
                and dump.get("journeys")
                and any(j for j in dump["journeys"].values())
                and any(e.get("kind") == "fault-fire"
                        and e.get("point") == pt
                        for e in dump.get("events", ()))
                and dump.get("budget"))
    report["flight"] = {
        "dumps_total": sum(by_reason.values()),
        "by_reason": by_reason,
        "spool_dir": obsf.FLIGHT.spool_dir(),
        "per_fault": per_fault,
        "content_ok": content_ok,
        "ok": (bool(per_fault)
               and all(n >= 1 for n in per_fault.values())
               and all(content_ok.values())),
    }

    cont_ok = all(
        c.get("recovered") for c in report["continuity"].values()
        if c.get("recovered") is not None)     # skipped scenarios pass
    if continuity_only:
        report["all_recovered"] = (cont_ok
                                   and report.get("metrics_visible", False)
                                   and report["flight"]["ok"])
    else:
        report["all_recovered"] = (
            all(f.get("recovered") for f in report["faults"].values())
            and report.get("content_quality", {}).get("recovered", False)
            and report.get("hostile_client", {}).get("recovered", False)
            and report.get("damage_spike", {}).get("recovered", False)
            and report["degrade"].get("breach", {}).get("recovered", False)
            and report["degrade"].get("remb_cap", {}).get("recovered",
                                                          False)
            and cont_ok
            and report.get("metrics_visible", False)
            and report["flight"]["ok"])
    return report


async def _ws_stall_scenario(cfg, session, port, frags,
                             recovery_budget_s: float) -> dict:
    """A stalled websocket client is evicted; the session keeps serving
    everyone else and the evicted client reconnects cleanly."""
    import aiohttp

    from .session import SubscriberSet

    t0 = time.perf_counter()
    evicted = False
    reconnected = False
    fired = 0
    async with aiohttp.ClientSession() as http:
        async with http.ws_connect(f"http://127.0.0.1:{port}/ws",
                                   max_msg_size=0) as ws:
            await ws.receive_json(timeout=recovery_budget_s)   # hello
            # a truly wedged client drains (essentially) nothing: the
            # stall must be long relative to the publish rate, or each
            # drained item frees a slot and resets the slow streak
            stall_fires = SubscriberSet.SLOW_EVICT_STREAK + 40
            rfaults.arm("ws_send_stall", count=stall_fires,
                        delay_ms=5000.0)
            deadline = time.perf_counter() + recovery_budget_s * 2
            while time.perf_counter() < deadline:
                msg = await ws.receive(
                    timeout=max(0.1, deadline - time.perf_counter()))
                if msg.type == aiohttp.WSMsgType.TEXT \
                        and '"evicted"' in msg.data:
                    evicted = True
                    break
                if msg.type in (aiohttp.WSMsgType.CLOSED,
                                aiohttp.WSMsgType.CLOSE,
                                aiohttp.WSMsgType.ERROR):
                    break
        fired = stall_fires - rfaults.armed_count("ws_send_stall")
        rfaults.disarm("ws_send_stall")
        # reconnect grace: the same client re-joins immediately
        async with http.ws_connect(f"http://127.0.0.1:{port}/ws",
                                   max_msg_size=0) as ws2:
            hello = await ws2.receive_json(timeout=recovery_budget_s)
            reconnected = hello.get("type") == "hello"
    # the in-process subscriber must have kept flowing throughout
    flowing = await _await_frag(frags, time.perf_counter(),
                                recovery_budget_s)
    return {"fired": fired,
            "recovered": bool(evicted and reconnected
                              and flowing is not None),
            "evicted": evicted, "reconnected": reconnected,
            "recovery_ms": round((time.perf_counter() - t0) * 1e3, 1)}


async def _degrade_scenario(cfg, session,
                            recovery_budget_s: float) -> dict:
    """Drive the degradation ladder with a fast-tick controller bound to
    the live session: an RTCP loss burst engages it, a sustained
    collect-stage breach walks it further down, and both restore."""
    ctl = DegradeController(
        SessionExecutor(session, cfg=cfg),
        window=60, min_frames=8, breach_ticks=2, recover_ticks=3,
        cooldown_s=0.1,
        # qp/fps only under --quick-ish budgets: the res_down rung
        # recompiles a fresh geometry, which the full run exercises via
        # the dynamic-resize path already covered by tier-1 tests
        max_level=3)
    out = {"ladder": [s.name for s in ctl.steps]}

    async def tick_until(pred, budget_s: float) -> bool:
        deadline = time.perf_counter() + budget_s
        while time.perf_counter() < deadline:
            ctl.tick()
            if pred():
                return True
            await asyncio.sleep(0.1)
        return False

    try:
        # Calibrate the budget to the ORGANIC baseline of this host: a
        # loaded CI box may serve the tiny geometry slower than the
        # absolute rung budget, and that steady state must not read as
        # a breach — the scenario tests the ladder's REACTION to an
        # injected regression, not the host's absolute speed.
        deadline = time.perf_counter() + recovery_budget_s
        while ctl.p50_ms() is None and time.perf_counter() < deadline:
            await asyncio.sleep(0.1)
        organic = ctl.p50_ms() or 0.0
        budget = max(ctl.budget_ms() or 1000.0 / max(cfg.refresh, 1),
                     organic * 3.0)
        ctl.set_budget_ms(budget)
        out["organic_p50_ms"] = round(organic, 1)
        out["budget_ms"] = round(budget, 1)

        # -- loss burst: engage at least the first rung ---------------
        burst = 400
        rfaults.arm("peer_rtcp_loss_burst", count=burst)
        t0 = time.perf_counter()
        engaged = await tick_until(lambda: ctl.level > 0,
                                   recovery_budget_s)
        fired = burst - rfaults.armed_count("peer_rtcp_loss_burst")
        rfaults.disarm("peer_rtcp_loss_burst")
        restored = await tick_until(lambda: ctl.level == 0,
                                    recovery_budget_s)
        out["loss_burst"] = {
            "fired": fired, "engaged": engaged,
            "recovered": bool(engaged and restored),
            "recovery_ms": round((time.perf_counter() - t0) * 1e3, 1)}

        # -- sustained budget breach: collect stage inflated past the
        #    calibrated budget until the ladder sheds quality ----------
        rfaults.arm("collect_timeout", count=100000, mode="slow",
                    delay_ms=budget * 3.0)
        t0 = time.perf_counter()
        max_level = 0

        def note_level():
            nonlocal max_level
            max_level = max(max_level, ctl.level)
            return ctl.level >= min(2, len(ctl.steps))

        engaged = await tick_until(note_level, recovery_budget_s * 2)
        rfaults.disarm("collect_timeout")
        restored = await tick_until(lambda: ctl.level == 0,
                                    recovery_budget_s * 2)
        out["breach"] = {
            "engaged": engaged, "max_level": max_level,
            "recovered": bool(engaged and restored),
            "recovery_ms": round((time.perf_counter() - t0) * 1e3, 1)}
        out["transitions"] = ctl.transitions
    finally:
        ctl.stop()
        # belt and braces: whatever the scenario left engaged, undo
        session.set_qp_offset(0)
        session.set_fps_cap(None)
    return out

async def _hostile_client_scenario(session, port, frags,
                                   recovery_budget_s: float) -> dict:
    """Hostile-wire co-tenancy (ISSUE 18 acceptance): one /ws peer
    floods spoofed journey acks and malformed control JSON until the
    ingress governor walks it WARN -> QUARANTINE -> EVICT (both rungs
    visible at /debug/events, the eviction with a flight-recorder dump
    through the shed path), while a LEGIT co-tenant on the same session
    keeps receiving media with its real fprobe acks accepted the whole
    time.  Component floods cover the media-plane vectors a loopback ws
    client cannot carry: a NACK storm against the RTCP monitor (17x BLP
    amplification capped by the per-peer budget) and a malformed-SCTP
    barrage that must neither raise nor grow the reassembly buffer."""
    import aiohttp

    from ..obs import flight as obsf
    from ..resilience import ingress as ringress
    from ..webrtc import rtcp as rtcp_mod
    from ..webrtc import sctp as sctp_mod

    t0 = time.perf_counter()
    out: dict = {}
    legit = {"frames": 0, "acks": 0, "evicted": False, "err": None}
    stop = asyncio.Event()

    async def legit_client(http) -> None:
        try:
            async with http.ws_connect(f"http://127.0.0.1:{port}/ws",
                                       max_msg_size=0) as ws:
                while not stop.is_set():
                    msg = await ws.receive(timeout=recovery_budget_s)
                    if msg.type == aiohttp.WSMsgType.BINARY:
                        legit["frames"] += 1
                    elif msg.type == aiohttp.WSMsgType.TEXT:
                        if '"evicted"' in msg.data or '"shed"' in msg.data:
                            legit["evicted"] = True
                            return
                        try:
                            ctrl = json.loads(msg.data)
                        except ValueError:
                            continue
                        if ctrl.get("type") == "fprobe":
                            # the honest ack path: echo the REAL fid
                            await ws.send_json(
                                {"type": "ack", "id": ctrl["id"]})
                            legit["acks"] += 1
                    elif msg.type in (aiohttp.WSMsgType.CLOSED,
                                      aiohttp.WSMsgType.CLOSE,
                                      aiohttp.WSMsgType.ERROR):
                        legit["evicted"] = True
                        return
        except Exception as e:          # noqa: BLE001 - reported below
            legit["err"] = repr(e)

    hostile = {"sent": 0, "shed_seen": False, "closed": False}

    async def hostile_reader(ws) -> None:
        try:
            while True:
                msg = await ws.receive(timeout=recovery_budget_s)
                if msg.type == aiohttp.WSMsgType.TEXT \
                        and '"shed"' in msg.data:
                    hostile["shed_seen"] = True
                elif msg.type in (aiohttp.WSMsgType.CLOSED,
                                  aiohttp.WSMsgType.CLOSE,
                                  aiohttp.WSMsgType.ERROR):
                    hostile["closed"] = True
                    return
        except (asyncio.TimeoutError, Exception):  # noqa: BLE001
            hostile["closed"] = True

    async with aiohttp.ClientSession() as http:
        legit_task = asyncio.ensure_future(legit_client(http))
        # let the legit client settle into the media flow first
        deadline = time.perf_counter() + recovery_budget_s
        while legit["frames"] < 3 and time.perf_counter() < deadline:
            await asyncio.sleep(0.05)
        frames_before = legit["frames"]

        async with http.ws_connect(f"http://127.0.0.1:{port}/ws",
                                   max_msg_size=0) as ws:
            reader = asyncio.ensure_future(hostile_reader(ws))
            try:
                # alternate spoofed acks (never-issued fids) with
                # malformed JSON; the flood deliberately overruns the
                # signal budget and then hammers through quarantine,
                # which is what walks the score to the evict rung
                for i in range(600):
                    if hostile["shed_seen"] or hostile["closed"]:
                        break
                    if i % 2:
                        await ws.send_str('{"type": "ack", "id": '
                                          + str(10 ** 9 + i) + "}")
                    else:
                        await ws.send_str('{"broken json %d' % i)
                    hostile["sent"] += 1
                    if i % 50 == 49:
                        # pace the flood against the media clock: the
                        # isolation claim is "legit frames keep landing
                        # WHILE the hostile peer hammers", so until the
                        # legit client makes progress each burst yields
                        # long enough for a frame interval to elapse —
                        # otherwise a cold pipeline can outlast a
                        # wall-clock-instant flood and the during-flood
                        # check races the first encode
                        burst_deadline = time.perf_counter() + 1.5
                        while legit["frames"] <= frames_before \
                                and time.perf_counter() < burst_deadline \
                                and not (hostile["shed_seen"]
                                         or hostile["closed"]):
                            await asyncio.sleep(0.05)
                        await asyncio.sleep(0)   # let the server run
                evict_deadline = time.perf_counter() + recovery_budget_s
                while not (hostile["shed_seen"] or hostile["closed"]) \
                        and time.perf_counter() < evict_deadline:
                    await asyncio.sleep(0.05)
            except (ConnectionResetError, RuntimeError):
                hostile["closed"] = True         # server closed mid-send
            finally:
                if not reader.done():
                    await asyncio.sleep(0.2)
                reader.cancel()

        frames_after_flood = legit["frames"]
        # the co-tenant must keep flowing AFTER the hostile eviction too
        flow_deadline = time.perf_counter() + recovery_budget_s
        while legit["frames"] <= frames_after_flood \
                and time.perf_counter() < flow_deadline:
            await asyncio.sleep(0.05)
        stop.set()
        await asyncio.wait_for(legit_task, recovery_budget_s)

        # ladder rungs must be CLIENT-visible on the fleet timeline,
        # and the boot-registered metric families must carry the counts
        async with http.get(
                f"http://127.0.0.1:{port}/debug/events") as resp:
            events_text = await resp.text()
        async with http.get(
                f"http://127.0.0.1:{port}/metrics") as resp:
            metrics_text = await resp.text()

    dump = obsf.FLIGHT.find_dump("shed", "ingress_evict")
    out["live"] = {
        "hostile_sent": hostile["sent"],
        "hostile_evicted": bool(hostile["shed_seen"]
                                or hostile["closed"]),
        "quarantine_visible": "ingress_quarantine" in events_text,
        "evict_visible": "ingress_evict" in events_text,
        "flight_dump": bool(dump),
        "violations_on_metrics":
            'dngd_ingress_violations_total{reason="ack_spoof"}'
            in metrics_text,
        "legit_frames": legit["frames"],
        "legit_acks": legit["acks"],
        "legit_flow_during_flood": frames_after_flood > frames_before,
        "legit_flow_after_evict": legit["frames"] > frames_after_flood,
        "legit_survived": not legit["evicted"] and legit["err"] is None,
    }

    # -- component: NACK storm against the RTCP monitor ----------------
    nack_budget = ringress.PeerBudget("hostile-nack")
    mon = rtcp_mod.PeerRtcpMonitor({0x1111: ("video", 90_000)})
    mon.budget = nack_budget
    delivered = []
    mon.on_nack = lambda kind, seqs: delivered.extend(seqs)
    try:
        media = struct.pack(">I", 0x1111)
        for i in range(200):
            # one FCI, full BLP: 17 expanded seqs per 16-byte packet
            pkt = (struct.pack(">BBH", 0x81, 205, 3)
                   + struct.pack(">I", 0xABAD1DEA) + media
                   + struct.pack(">HH", (i * 17) & 0xFFFF, 0xFFFF))
            mon.ingest(pkt)
        burst = max(ringress._RATE_KINDS["nack"][1] * 2.0, 10.0)
        out["nack_flood"] = {
            "sent_seqs": 200 * 17,
            "delivered_seqs": len(delivered),
            "capped": len(delivered) <= burst + 50,
        }
    finally:
        nack_budget.close()
        mon.close()

    # -- component: malformed-SCTP barrage -----------------------------
    # an ESTABLISHED association (matching vtag), so lying chunk
    # headers reach the chunk parser instead of the vtag drop
    sctp_budget = ringress.PeerBudget("hostile-sctp")
    to_srv: list = []
    to_cli: list = []
    assoc = sctp_mod.SctpAssociation(role="server",
                                     on_transmit=to_cli.append)
    cli = sctp_mod.SctpAssociation(role="client",
                                   on_transmit=to_srv.append)
    cli.connect()
    for _ in range(8):
        for pkt in to_srv:
            assoc.receive(pkt)
        to_srv.clear()
        for pkt in to_cli:
            cli.receive(pkt)
        to_cli.clear()
        if assoc.established and cli.established:
            break
    assoc.budget = sctp_budget
    vtag = assoc.local_tag
    try:
        violations0 = ringress._M_VIOLATIONS.labels(
            "sctp_malformed_chunk").value
        for i in range(300):
            kind = i % 3
            if kind == 0:                  # pure garbage
                pkt = bytes((i * 7 + j) & 0xFF for j in range(48))
            elif kind == 1:                # valid header, bad CRC
                pkt = (struct.pack(">HHI", 5000, 5000, vtag)
                       + b"\xff\xff\xff\xff"
                       + struct.pack(">BBH", 0, 3, 32) + b"x" * 28)
            else:                          # truncated DATA value: valid
                # framing + CRC, but too short for the chunk's own
                # fixed fields — the in-handler malformed path
                pkt = sctp_mod.pack_packet(
                    5000, 5000, vtag,
                    [sctp_mod.pack_chunk(sctp_mod.CT_DATA, 3, b"xx")])
            assoc.receive(pkt)
        out["sctp_malformed"] = {
            "sent": 300,
            "established": bool(assoc.established),
            "no_raise": True,
            "buf_bounded": assoc._rcv_buf_bytes <= assoc._rcv_buf_cap,
            "scored": ringress._M_VIOLATIONS.labels(
                "sctp_malformed_chunk").value > violations0,
            "governor_state": sctp_budget.state,
        }
    finally:
        sctp_budget.close()
        assoc._close("hostile barrage done")
        cli._close("hostile barrage done")

    live = out["live"]
    out["recovered"] = bool(
        live["hostile_evicted"]
        and live["quarantine_visible"] and live["evict_visible"]
        and live["flight_dump"] and live["violations_on_metrics"]
        and live["legit_survived"] and live["legit_flow_during_flood"]
        and live["legit_flow_after_evict"] and live["legit_acks"] >= 1
        and out["nack_flood"]["capped"]
        and out["sctp_malformed"]["no_raise"]
        and out["sctp_malformed"]["buf_bounded"]
        and out["sctp_malformed"]["scored"])
    out["recovery_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    return out
