"""Streaming session: capture -> TPU encode -> fMP4 -> connected clients.

The selkies pipeline equivalent (reference SURVEY.md §3.2 hot path:
``ximagesrc ! videoconvert ! nvh264enc ! rtph264pay ! webrtcbin``), rebuilt
as: FrameSource -> flagship H.264 encoder (pipelined submit/collect so the
host->device upload of frame N+1 overlaps frame N's device entropy — the
§3.2 double-buffering requirement) -> Mp4Muxer -> fan-out to subscriber
queues (one per websocket client).

The session runs on a private thread (JAX dispatch blocks; keeping it off
the event loop keeps signaling responsive) and publishes into asyncio via
``loop.call_soon_threadsafe``.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
import weakref
from typing import Optional

from ..models import make_encoder
from ..obs import budget as obsb
from ..obs import events as obsev
from ..obs import journey as obsj
from ..obs import metrics as obsm
from ..obs.trace import next_frame_id, tracer
from ..resilience import continuity as rcont
from ..resilience import faults as rfaults
from ..resilience.policy import CircuitBreaker, RetryPolicy
from ..utils.config import Config
from ..utils.timing import FrameStats, percentile
from .mp4 import Mp4Muxer, split_annexb

log = logging.getLogger(__name__)

__all__ = ["StreamSession", "SubscriberSet", "keyframe_requester"]


def keyframe_requester(session):
    """The ``fn(reason)`` to wire into a WebRTC peer's
    ``on_keyframe_request``: the session's rate-limited ``request_idr``
    when it has one (StreamSession, SessionHub), the legacy unlimited
    ``request_keyframe`` otherwise (reason dropped), or None for
    sessions with no keyframe surface at all.  One definition — the
    /ws offer path and the stock-selkies shim both wire through it."""
    if hasattr(session, "request_idr"):
        return session.request_idr
    if hasattr(session, "request_keyframe"):
        return lambda reason: session.request_keyframe()
    return None

# -- telemetry (obs registry; see obs/__init__ for the naming scheme) ----
_M_SUBMIT_MS = obsm.histogram(
    "dngd_encoder_submit_ms",
    "Capture + host color conversion + async device dispatch per frame")
_M_COLLECT_MS = obsm.histogram(
    "dngd_encoder_collect_ms",
    "Device wait + bitstream pull + AU assembly per frame")
_M_FRAMES = obsm.counter(
    "dngd_encoder_frames_total", "Encoded frames delivered to fan-out")
_M_BYTES = obsm.counter(
    "dngd_encoder_bytes_total", "Muxed media bytes delivered to fan-out")
_M_COLLECT_FAIL = obsm.counter(
    "dngd_encoder_collect_failures_total",
    "encode_collect failures (frame dropped, IDR resync engaged)")
_M_DROPPED = obsm.counter(
    "dngd_session_dropped_frags_total",
    "Media fragments evicted from slow subscriber queues")
_M_SLOW = obsm.counter(
    "dngd_session_slow_subscriber_events_total",
    "Publishes that hit a full subscriber queue (backpressure engaged)")
_M_EVICTED = obsm.counter(
    "dngd_session_evicted_subscribers_total",
    "Subscribers evicted after a sustained slow streak (reconnect "
    "re-admits them with a fresh IDR-gated queue)")
_M_SUBMIT_FAIL = obsm.counter(
    "dngd_encoder_submit_failures_total",
    "encode_submit failures (frame dropped; breaker-counted — the "
    "session stops only when the device is declared dead)")
_M_SOURCE_FAIL = obsm.counter(
    "dngd_session_source_failures_total",
    "Frame-source grab failures (X server gone; retried with backoff)")
_M_KEYFRAMES = obsm.counter(
    "dngd_encoder_keyframes_total",
    "Keyframes delivered to fan-out (IDR resyncs land here)")
M_IDR_REQUESTS = obsm.counter(
    "dngd_idr_requests_total",
    "Forced-IDR requests through the session's rate-limited "
    "request_idr path, by reason (pli/fir = client feedback, resync = "
    "collect-failure recovery, degrade = ladder rung, evict = "
    "keyframe lost to queue eviction)", ("reason",))

# Queue depth / client count are scrape-time functions over the live
# SubscriberSets — zero hot-path cost, always-current value.
_ALL_SUBSCRIBER_SETS: "weakref.WeakSet" = weakref.WeakSet()
_M_QDEPTH = obsm.gauge(
    "dngd_session_queue_depth",
    "Queued media/control items across all subscriber queues")
_M_QDEPTH.set_function(
    lambda: sum(s.queue_depth() for s in list(_ALL_SUBSCRIBER_SETS)))
_M_CLIENTS = obsm.gauge(
    "dngd_session_clients", "Connected media subscribers")
_M_CLIENTS.set_function(
    lambda: sum(len(s) for s in list(_ALL_SUBSCRIBER_SETS)))


class _Sub:
    __slots__ = ("q", "want_key", "slow_streak")

    def __init__(self, q: asyncio.Queue, want_key: bool):
        self.q = q
        self.want_key = want_key
        self.slow_streak = 0     # consecutive publishes that hit full


class SubscriberSet:
    """Per-session client fan-out: asyncio queue per subscriber with
    latest-wins backpressure (slow clients shed their OLDEST fragment, the
    way the reference's RTP path sheds late media).

    GOP-aware: a subscriber created with ``want_key=True`` receives no
    media fragment until its first keyframe (a mid-GOP joiner must not
    see undecodable P fragments), and when eviction drops a keyframe the
    subscriber is re-gated and :meth:`publish` returns True so the caller
    can ask the encoder for a fresh IDR.

    A subscriber whose queue is full for ``SLOW_EVICT_STREAK``
    *consecutive* publishes is evicted outright (its queue gets one
    final ``("evicted", reason)`` control item the websocket layer turns
    into a close): per-item eviction protects the other clients' memory,
    but a permanently wedged client still costs an IDR storm every
    cooldown and a queue of garbage.  Reconnect grace: eviction carries
    no penalty — the same client reconnecting is re-admitted immediately
    with a fresh IDR-gated queue (the normal join path)."""

    # ~0.5 s of sustained stall at 60 fps before eviction; one drained
    # item resets the streak, so bursty-but-alive clients never trip it
    SLOW_EVICT_STREAK = 30

    def __init__(self):
        self._subs: list = []
        _ALL_SUBSCRIBER_SETS.add(self)

    def close(self) -> None:
        """Session teardown: drop every subscriber and deregister from
        the scrape-time gauges NOW instead of waiting for GC — a long-
        running server churning thousands of sessions must not carry
        dead sets in the queue-depth/client-count reads."""
        self._subs = []
        _ALL_SUBSCRIBER_SETS.discard(self)

    def queue_depth(self) -> int:
        """Items currently queued across this set's subscribers (the
        `/metrics` queue-depth gauge reads this at scrape time)."""
        return sum(s.q.qsize() for s in self._subs)

    def __len__(self) -> int:
        return len(self._subs)

    def __bool__(self) -> bool:
        return bool(self._subs)

    def subscribe(self, first_items=(), maxsize: int = 8,
                  want_key: bool = False) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)
        for item in first_items:
            q.put_nowait(item)
        self._subs.append(_Sub(q, want_key))
        return q

    def unsubscribe(self, q: asyncio.Queue) -> None:
        self._subs = [s for s in self._subs if s.q is not q]

    @staticmethod
    def _drop_frags(q: asyncio.Queue) -> bool:
        """Drop media frags up to the next queued keyframe (they follow a
        dropped keyframe and cannot be decoded); keep control items, and
        keep a later queued keyframe plus its successors — that is a
        valid recovery point.  Returns True if a keyframe was retained."""
        keep, kept_key, dropped = [], False, 0
        while True:
            try:
                it = q.get_nowait()
            except asyncio.QueueEmpty:
                break
            if it[0] != "frag" or kept_key:
                keep.append(it)
            elif len(it) > 2 and it[2]:
                kept_key = True
                keep.append(it)
            else:
                dropped += 1
        for it in keep:
            q.put_nowait(it)
        if dropped:
            _M_DROPPED.inc(dropped)
        return kept_key

    def publish(self, item, keyframe=None) -> bool:
        """Fan ``item`` out to every subscriber.

        ``keyframe``: None for control items (never gated), else whether
        this media frag is a keyframe.  Returns True when any subscriber
        lost a keyframe to eviction (caller should request a new IDR)."""
        need_idr = False
        for sub in list(self._subs):
            if keyframe is not None and sub.want_key and not keyframe:
                continue                 # undecodable until the next IDR
            slow_counted = False
            while True:
                try:
                    sub.q.put_nowait(item)
                    if keyframe:
                        sub.want_key = False
                    break
                except asyncio.QueueFull:
                    if not slow_counted:
                        slow_counted = True
                        _M_SLOW.inc()
                    try:
                        old = sub.q.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    if old[0] == "frag":
                        _M_DROPPED.inc()
                    if old[0] == "frag" and len(old) > 2 and old[2]:
                        # Evicted this client's keyframe: frags queued
                        # before the NEXT keyframe (if any) are garbage.
                        if self._drop_frags(sub.q):
                            continue     # queued IDR is a recovery point
                        if keyframe:
                            continue     # incoming IDR replaces it
                        sub.want_key = True
                        need_idr = True
                        if keyframe is False:
                            break        # withhold the undecodable P frag
                        # control item (keyframe=None): retry the enqueue
            if slow_counted:
                sub.slow_streak += 1
                if sub.slow_streak >= self.SLOW_EVICT_STREAK:
                    self._evict(sub, "slow-subscriber")
            else:
                sub.slow_streak = 0
        return need_idr

    def _evict(self, sub: _Sub, reason: str) -> None:
        """Drop a wedged subscriber: drain its queue, leave one
        ``("evicted", reason)`` control item (the ws layer sends it and
        closes), and forget it.  The client reconnects through the
        normal join path — that IS the reconnect grace."""
        self._subs = [s for s in self._subs if s is not sub]
        while True:
            try:
                sub.q.get_nowait()
            except asyncio.QueueEmpty:
                break
        sub.q.put_nowait(("evicted", reason))
        _M_EVICTED.inc()
        log.warning("evicted subscriber after %d consecutive slow "
                    "publishes (%s); reconnect is immediate",
                    sub.slow_streak, reason)

    def broadcast_all(self, items) -> None:
        """Deliver a sequence atomically-ish to every queue (resize
        re-announcements); drops on full rather than evicting."""
        for sub in list(self._subs):
            try:
                for item in items:
                    sub.q.put_nowait(item)
            except asyncio.QueueFull:
                pass


class StreamSession:
    """One desktop's encode-and-fan-out loop."""

    def __init__(self, cfg: Config, source, loop=None, clock=None):
        from .clock import MediaClock

        self.cfg = cfg
        self.source = source
        self.loop = loop
        self.clock = clock if clock is not None else MediaClock()
        self.stats = FrameStats()
        # degradation-ladder state (resilience/degrade executes through
        # these): must exist before the first _setup_codec
        self._qp_offset = 0
        self._fps_cap: Optional[float] = None
        self._setup_codec(source.width, source.height)
        self._subscribers = SubscriberSet()
        # raw-AU taps (WebRTC peers): fn(annexb_au, keyframe, pts90k),
        # called on the encode thread
        self._au_listeners: list = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._prewarm = None
        self._last_seq = -1
        self._need_frame = False
        # set on a collect failure: suppress delivery of in-flight P
        # frames (they predict from a reference the client never got)
        # until the encoder's forced-IDR resync comes through
        self._drop_until_key = False
        # healthz liveness: the loop made PROGRESS (delivered a frame or
        # was legitimately idle) — a loop spinning on encode failures
        # does not refresh this and goes unhealthy after the stall window
        self._last_tick = time.monotonic()
        self._pending_resize: Optional[tuple] = None
        self._resize_lock = threading.Lock()
        # rate-limited forced-IDR path (request_idr): PLI/FIR feedback,
        # the collect-failure resync and the degrade ladder's IDR rung
        # all dedupe here — a PLI storm costs ONE keyframe per window,
        # over-limit requests collapse into a single deferred grant
        self._idr_lock = threading.Lock()
        self._idr_last_grant = -1e9
        self._idr_deferred = False
        # submit failures are breaker-counted: isolated failures drop
        # one frame each; a run of consecutive failures (device genuinely
        # gone) opens the breaker — which no longer kills the session:
        # it enters device-loss RECOVERY (re-acquire + checkpoint
        # restore), with the breaker's half-open probe pacing the
        # re-acquire attempts.  The short reset timeout is the probe
        # cadence, not a death sentence.
        self._submit_breaker = CircuitBreaker(failure_threshold=8,
                                              reset_timeout_s=2.0)
        # frame-source failures (X server gone) retry with capped
        # backoff until the supervisor brings the server back
        self._source_policy = RetryPolicy(initial=0.05, cap=1.0)
        self._source_failures = 0
        # session continuity (resilience/continuity): host-side encoder
        # checkpoints on a cadence; device loss restores the SAME stream
        # lineage (muxer, clock, subscribers, AU listeners — and with
        # them SSRC/seq/timestamps) onto a re-acquired device
        self._ckpt = rcont.CheckpointKeeper(
            getattr(cfg, "ckpt_interval_s", 5.0))
        self._recovery_policy = RetryPolicy(initial=0.25, cap=2.0,
                                            max_attempts=40)
        self._recoveries = 0
        # zero-downtime handoff (resilience/handoff): a predecessor's
        # exported lineage parks here (loop side, lock-guarded like
        # _pending_resize) until the encode thread adopts it between
        # frames — import_state is never called cross-thread
        self._pending_adopt: Optional[dict] = None
        self._adopt_lock = threading.Lock()
        self._handoff_adopted = False
        from collections import deque
        self._submit_ms: deque = deque(maxlen=600)
        self._collect_ms: deque = deque(maxlen=600)
        # per-frame trace spans land in the process 'pipeline' ring
        # buffer, exported at /debug/trace (obs/trace)
        self._tracer = tracer("pipeline")
        # glass-to-glass frame journeys (obs/journey): minted at
        # capture, chunk/shard-stamped at collect, closed by the client
        # (ws ack or the peer's RTCP highest-seq).  Public: the /ws ack
        # handler and the WebRTC peer close through this book.
        self.journeys = obsj.JourneyBook()
        # CPU-energy proxy published to /metrics per tune tier (obs/
        # procstats) — continuously scrapeable, not a bench-only number
        from ..obs.procstats import CpuEnergyMeter, register_energy_gauges
        register_energy_gauges()   # family scrapeable before 1st publish
        self._energy = CpuEnergyMeter()
        self._energy_frames = 0

    # After a codec (re)build the next encode jit-compiles the new
    # geometry, which can exceed HEALTHZ_STALL_S on a cold cache; the
    # liveness probe must not kill the pod mid-compile.
    COMPILE_GRACE_S = 180.0

    def _setup_codec(self, width: int, height: int) -> None:
        self._healthz_grace_until = time.monotonic() + self.COMPILE_GRACE_S
        self.encoder, self.codec_name = make_encoder(self.cfg, width, height)
        # super-step ring encoders stage chunk+1 frames in flight (the
        # chunk dispatches as ONE device program); classic codecs keep 2
        self.PIPELINE_DEPTH = getattr(self.encoder, "pipeline_depth", 2)
        if self._qp_offset:
            # degradation survives a codec rebuild (resize mid-degrade)
            self.encoder.degrade_qp_offset = self._qp_offset
        # The budget ledger's SLO verdicts gate against the BASELINE rung
        # matching the LIVE geometry/rate (obs/budget); resizes re-aim it.
        obsb.LEDGER.set_context(width, height, self.cfg.refresh)
        if self.codec_name.startswith("h264"):
            sps, pps = self._sps_pps()
            self.muxer = Mp4Muxer(width, height, sps, pps,
                                  fps=self.cfg.refresh)
            self.init_segment = self.muxer.init_segment()
        elif self.codec_name.startswith("vp8"):
            # VP8 over MSE rides WebM clusters (mp4 has no VP8 story)
            from .webm import WebmMuxer
            self.muxer = WebmMuxer(width, height, fps=self.cfg.refresh)
            self.init_segment = self.muxer.init_segment()
        else:
            # MJPEG transport: each binary message is one JPEG; the client
            # paints frames directly (no MSE, no init segment).
            self.muxer = None
            self.init_segment = b""

    def hello(self) -> dict:
        """The client handshake message (sent on join and after resize)."""
        return {
            "type": "hello",
            "codec": self.codec_name,
            "mime": self.mime,
            "width": self.source.width,
            "height": self.source.height,
        }

    # -- dynamic resize (WEBRTC_ENABLE_RESIZE, reference Dockerfile:211) --

    def request_resize(self, width: int, height: int) -> bool:
        """Queue a resolution change; applied by the encode thread between
        frames (the kernels are geometry-parameterized — a new geometry is
        one new jit specialization, SURVEY.md §5 long-context analog)."""
        if not self.cfg.webrtc_enable_resize:
            return False
        if not hasattr(self.source, "resize"):
            return False
        width, height = int(width), int(height)
        if not (16 <= width <= 7680 and 16 <= height <= 4320):
            return False
        with self._resize_lock:
            self._pending_resize = (width, height)
        return True

    def _apply_resize(self) -> None:
        with self._resize_lock:
            pending = self._pending_resize
            self._pending_resize = None
        if pending is None:
            return
        w, h = pending
        if (w, h) == (self.source.width, self.source.height):
            return
        log.info("resizing session to %dx%d", w, h)
        self.source.resize(w, h)
        self._setup_codec(w, h)
        # the qp-ladder prewarm is geometry-specific: stop the old
        # encoder's walk and start one for the fresh (cold-cache) encoder
        self._restart_prewarm()
        self._last_seq = -1
        hello = self.hello()
        init = self.init_segment

        items = [("json", hello)] + ([("init", init)] if init else [])
        if self.loop is not None:
            self.loop.call_soon_threadsafe(
                self._subscribers.broadcast_all, items)
        else:
            self._subscribers.broadcast_all(items)

    def _sps_pps(self):
        nals = split_annexb(self.encoder.headers())
        sps = next(n for n in nals if (n[0] & 0x1F) == 7)
        pps = next(n for n in nals if (n[0] & 0x1F) == 8)
        return sps, pps

    @property
    def mime(self) -> str:
        """Muxer-declared MSE type, or the direct-paint MJPEG type."""
        return "image/jpeg" if self.muxer is None else self.muxer.mime

    # -- client fan-out --------------------------------------------------

    def subscribe(self, maxsize: int = 8) -> asyncio.Queue:
        """Register a client; first queue item is always the init segment.
        The encoder is asked for an IDR so the client can join mid-stream
        (SURVEY.md §5 'resume = force IDR'), and the queue is gated until
        that keyframe arrives — a mid-GOP joiner never sees P frags it
        cannot decode."""
        first = [("init", self.init_segment)] if self.init_segment else []
        q = self._subscribers.subscribe(first, maxsize=maxsize,
                                        want_key=True)
        self.request_keyframe()
        return q

    def unsubscribe(self, q: asyncio.Queue) -> None:
        self._subscribers.unsubscribe(q)

    def request_keyframe(self) -> None:
        """Force an IDR *and* wake the encode loop: on an idle desktop
        the damage gate would otherwise skip encoding forever, leaving a
        gated new joiner with no picture.  Unconditional — the join
        path must never defer (a gated subscriber has no picture until
        its IDR); rate-limitable reasons go through :meth:`request_idr`."""
        self.encoder.request_keyframe()
        self._need_frame = True

    # One forced IDR per window across every dedupe-able reason: a
    # misbehaving client PLI-storming the feedback channel must not
    # cost all other clients an IDR-bitrate storm (each IDR is ~10x a
    # P frame), and PLI / collect-resync / ladder requests racing each
    # other should collapse into the single keyframe that serves all.
    IDR_MIN_INTERVAL_S = 1.0

    def request_idr(self, reason: str = "manual") -> bool:
        """Rate-limited, deduped forced-IDR request.

        Returns True when the request was granted immediately; an
        over-limit request is DEFERRED (not dropped): the encode loop
        grants one collapsed IDR once the window reopens, so a resync
        requested right after a PLI-granted keyframe still happens —
        at most ``IDR_MIN_INTERVAL_S`` late."""
        M_IDR_REQUESTS.labels(reason).inc()
        now = time.monotonic()
        with self._idr_lock:
            if now - self._idr_last_grant >= self.IDR_MIN_INTERVAL_S:
                self._idr_last_grant = now
                self._idr_deferred = False
                grant = True
            else:
                self._idr_deferred = True
                grant = False
        if grant:
            self.request_keyframe()
        return grant

    def _idr_tick(self) -> None:
        """Encode-loop side of :meth:`request_idr`: grant the collapsed
        deferred request once the rate window reopens."""
        with self._idr_lock:
            if not self._idr_deferred:
                return
            now = time.monotonic()
            if now - self._idr_last_grant < self.IDR_MIN_INTERVAL_S:
                return
            self._idr_deferred = False
            self._idr_last_grant = now
        self.request_keyframe()

    # -- degradation executors (resilience/degrade walks these) --------

    def set_qp_offset(self, offset: int) -> None:
        """Bias the encoder's effective qp by ``offset`` (0 restores).
        Applied on the NEXT frame; survives resizes.  Each distinct qp
        is one jit specialization, so the ladder moves in one coarse
        step rather than a continuum — and the first engagement may pay
        that compile on the encode thread (prewarm covers the offset
        ladder when enabled, but CQP sessions never prewarm): grant the
        same healthz grace a codec rebuild gets, or the liveness probe
        kills a pod for degrading correctly."""
        self._qp_offset = int(offset)
        self.encoder.degrade_qp_offset = self._qp_offset
        if self._qp_offset:
            self._healthz_grace_until = max(
                self._healthz_grace_until,
                time.monotonic() + self.COMPILE_GRACE_S)

    def set_fps_cap(self, fps: Optional[float]) -> None:
        """Cap the encode loop's frame rate below the configured refresh
        (None restores).  Read by the loop every iteration, so the cap
        lands within one frame interval."""
        self._fps_cap = None if fps is None else max(float(fps), 1.0)

    # -- raw access-unit taps (the WebRTC media plane's input) ---------

    def add_au_listener(self, fn) -> None:
        """Register fn(annexb_au, keyframe, pts90k); runs on the encode
        thread — listeners must marshal to their own loop."""
        self._au_listeners.append(fn)
        self.request_keyframe()

    def remove_au_listener(self, fn) -> None:
        if fn in self._au_listeners:
            self._au_listeners.remove(fn)

    def _publish(self, fragment: bytes, keyframe: bool,
                 fid: int = 0) -> None:
        # the 4th tuple element is the frame-journey id: the websocket
        # pump probes sampled fids and the client's ack closes the
        # journey (obs/journey)
        if self._subscribers.publish(("frag", fragment, keyframe, fid),
                                     keyframe=keyframe):
            # A permanently stalled client would otherwise evict its
            # keyframe every queue-depth frames and storm the encoder
            # with IDR requests (IDRs cost every OTHER client
            # bitrate); request_idr's shared window IS the cap.
            self.request_idr("evict")

    # -- encode loop ------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="stream-session")
        self._thread.start()
        self._restart_prewarm()

    def _restart_prewarm(self) -> None:
        """(Re)start the background qp-ladder compile for the CURRENT
        encoder — the ladder's executables are geometry- and qp-specific,
        so a resize needs a fresh walk and the old one stopped."""
        if self._prewarm is not None:
            self._prewarm[1].set()
            self._prewarm = None
        if (self.cfg.encoder_prewarm
                and getattr(self.encoder, "_rate", None) is not None
                and hasattr(self.encoder, "prewarm_async")):
            self._prewarm = self.encoder.prewarm_async()

    def stop(self) -> None:
        self._stop.set()
        if self._prewarm is not None:
            self._prewarm[1].set()       # abort between ladder steps
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._prewarm is not None:
            # a daemon thread mid-JAX-compile at interpreter exit aborts
            # the process; give the in-flight ladder step a chance to
            # finish before teardown proceeds
            self._prewarm[0].join(timeout=30)
            self._prewarm = None

    def close(self) -> None:
        """Full teardown: stop the encode thread AND release every piece
        of per-session observability state.  A server churning thousands
        of sessions must end each one with this (not bare ``stop()``) or
        the registry accumulates dead entries: the subscriber set stays
        in the queue-depth/client gauges until GC, the budget ledger
        keeps gating SLO rungs against a geometry that no longer serves,
        and AU listeners pin their peers."""
        self.stop()
        self._au_listeners.clear()
        self._subscribers.close()
        self.journeys.close_book()
        obsb.LEDGER.clear_context()
        try:
            from ..obs.content import PLANE as _content
            _content.drop(self.journeys.session)
        except Exception:
            pass

    # -- zero-downtime handoff (resilience/handoff) --------------------

    def export_handoff(self) -> dict:
        """This session's half of a process-handoff snapshot.  Call with
        the encode thread STOPPED (``stop()``): ``export_state`` walks
        encoder internals that are not safe against a running loop."""
        return {"encoder": self.encoder.export_state(),
                "codec": self.codec_name,
                "width": self.source.width,
                "height": self.source.height,
                "recoveries": self._recoveries,
                "session": self.journeys.session}

    def adopt_handoff(self, state: dict) -> None:
        """Queue a predecessor's exported lineage; the encode thread
        imports it between frames (the ``_pending_resize`` pattern).
        Safe before ``start()`` too — the first loop iteration adopts."""
        with self._adopt_lock:
            self._pending_adopt = state

    def _consume_adopt(self) -> None:
        """Encode-thread side of :meth:`adopt_handoff`.  A failed import
        (schema drift, geometry change between builds) degrades to a
        fresh lineage + keyframe — and emits ``handoff-failed`` so the
        flight recorder dumps why the deploy wasn't seamless."""
        with self._adopt_lock:
            state = self._pending_adopt
            self._pending_adopt = None
        if state is None:
            return
        from ..resilience import handoff as rhandoff
        ckpt = state.get("encoder") or {}
        try:
            self.encoder.import_state(ckpt)
        except Exception as e:
            log.warning("handoff adopt rejected (%s); continuing with a "
                        "fresh lineage", e)
            rhandoff.count_session("failed")
            obsev.emit("handoff-failed", reason="adopt_reject",
                       session=self.journeys.session, error=str(e))
            self.encoder.request_keyframe()
            return
        # the imported checkpoint becomes the latest: a device loss in
        # the first cadence window still restores the migrated lineage
        self._ckpt.adopt(ckpt)
        self._recoveries += int(state.get("recoveries") or 0)
        self._handoff_adopted = True
        rhandoff.count_session("imported")
        obsev.emit("handoff-adopted", session=self.journeys.session,
                   frame_index=ckpt.get("frame_index"),
                   predecessor=state.get("session"))
        log.info("adopted handoff lineage (frame_index=%s, codec=%s)",
                 ckpt.get("frame_index"), state.get("codec"))

    # -- device-loss recovery (resilience/continuity) ------------------

    def _recover_device(self) -> bool:
        """Re-acquire a device and restore the checkpointed lineage.

        Runs on the encode thread while the submit breaker is open.  The
        breaker's half-open probe paces the attempts: each ``allow()``
        grants one re-acquire try (rebuild encoder + device round-trip +
        checkpoint import — the import re-uploads reference planes, so a
        still-dead device fails HERE, re-opening the breaker for another
        cool-down).  The muxer, media clock, subscriber queues and AU
        listeners are untouched, so the restored stream keeps its init
        segment, timestamp timeline and (via the persistent WebRTC peer)
        SSRC and contiguous RTP sequence numbers; the client sees the
        recovery IDR as a glitch, not a teardown.  Returns False when
        the retry budget is exhausted or stop was requested."""
        t0 = time.monotonic()
        ckpt = self._ckpt.state
        attempt = 0
        # recovery IS progress: the liveness probe must not kill a pod
        # mid-re-acquire (a restart would only recover more slowly)
        self._healthz_grace_until = time.monotonic() + self.COMPILE_GRACE_S
        while not self._stop.is_set():
            if not self._submit_breaker.allow():
                time.sleep(0.05)             # open: cooling down
                continue
            try:
                enc, name = rcont.restore_encoder(
                    self.cfg, self.source.width, self.source.height, ckpt)
            except Exception:
                attempt += 1
                log.exception("device re-acquire attempt %d failed",
                              attempt)
                self._submit_breaker.record_failure()   # re-opens
                if self._recovery_policy.gives_up(attempt):
                    return False
                time.sleep(self._recovery_policy.delay(attempt - 1))
                continue
            if name != self.codec_name:
                # config-driven codec selection changed under us (e.g. a
                # fallback encoder); lineage cannot carry over — rebuild
                # the muxer path and let clients re-hello
                log.warning("recovered codec %s != %s; full codec "
                            "rebuild", name, self.codec_name)
                self._setup_codec(self.source.width, self.source.height)
            else:
                self.encoder = enc
                self._healthz_grace_until = (
                    time.monotonic() + self.COMPILE_GRACE_S)
            self._submit_breaker.record_success()
            self._restart_prewarm()
            self._need_frame = True          # wake the damage gate
            self._recoveries += 1
            elapsed = time.monotonic() - t0
            rcont.record_recovery(elapsed)
            log.warning(
                "device recovered in %.2fs (attempt %d, checkpoint %s); "
                "recovery IDR queued on the existing stream lineage",
                elapsed, attempt + 1,
                "age %.1fs" % self._ckpt.age_s if ckpt is not None
                else "absent")
            obsev.emit("device-recovered",
                       session=self.journeys.session,
                       elapsed_s=round(elapsed, 2),
                       attempts=attempt + 1)
            return True
        return False

    PIPELINE_DEPTH = 2   # frames in flight: upload/compute/pull overlap

    def _run(self) -> None:
        pending: list = []                   # submitted tokens, oldest first
        while not self._stop.is_set():
            # re-read each iteration: the degrade ladder caps the rate live
            rate = max(self.cfg.refresh, 1)
            if self._fps_cap is not None:
                rate = min(rate, self._fps_cap)
            frame_interval = 1.0 / rate
            if self._pending_adopt is not None:
                self._consume_adopt()
            if self._pending_resize is not None:
                while pending:               # drain old-geometry frames
                    try:
                        self.encoder.encode_collect(pending.pop(0)[0])
                    except Exception:
                        pass
                self._apply_resize()
            self._idr_tick()       # grant a deferred rate-limited IDR
            t0 = time.perf_counter()
            try:
                if rfaults.fire("xserver_gone") is not None:
                    raise ConnectionError("fault injection: xserver_gone")
                rgb, seq = self.source.frame()
            except Exception:
                # X server (or capture backend) gone: retry with capped
                # backoff — the supervisor is restarting it; a long
                # outage stops refreshing _last_tick and healthz flags
                # the pod, a short one recovers invisibly (plus an IDR
                # so clients resync to the revived desktop).
                if self._source_failures == 0:
                    log.exception("frame source failed; retrying with "
                                  "backoff")
                _M_SOURCE_FAIL.inc()
                self._source_failures += 1
                time.sleep(self._source_policy.delay(
                    self._source_failures - 1))
                continue
            if self._source_failures:
                log.info("frame source recovered after %d failures; "
                         "forcing IDR resync", self._source_failures)
                self._source_failures = 0
                self.request_keyframe()
            # A pending keyframe request (new joiner / evicted IDR)
            # overrides the damage gate: a static desktop must still
            # produce the IDR that un-gates the subscriber.
            changed = seq != self._last_seq or self._need_frame
            if not changed and not pending:
                # Legitimate idleness counts as liveness progress; a loop
                # stuck failing every encode does NOT (healthz catches it).
                self._last_tick = time.monotonic()
                # idle: poll gently, and barely at all with no clients
                # (each poll costs a grab + damage compare)
                time.sleep(frame_interval / 4 if self._subscribers
                           else min(frame_interval * 4, 0.25))
                continue
            self._need_frame = False
            self._last_seq = seq

            if changed:
                # pts stamped at CAPTURE (submit) so the A/V contract
                # aligns on when pixels existed, not when encode finished.
                # Unwrapped: the muxer timeline must never jump back; AU
                # listeners (RTP) reduce mod 2^32 themselves.
                capture_pts = self.clock.now90k_unwrapped()
                fid = next_frame_id()
                # journey minted at capture: this id survives through
                # the encoder, muxer, fan-out, and comes back in the
                # client's ack (or via the peer's RTCP seq mapping)
                self.journeys.mint(fid, pts=capture_pts, t_capture=t0)
                t_cap = time.perf_counter()
                try:
                    if rfaults.fire("device_submit_error") is not None:
                        raise RuntimeError(
                            "fault injection: device_submit_error")
                    if rfaults.fire("device_preempt") is not None:
                        # a preemption notice is unambiguous — no point
                        # counting 8 failures against a revoked device
                        self._submit_breaker.trip()
                        raise RuntimeError(
                            "fault injection: device_preempt "
                            "(device revoked)")
                    token = self.encoder.encode_submit(rgb)
                except Exception:
                    # One failed submit drops one frame (nothing is in
                    # flight for it); a consecutive run — a device that
                    # is actually gone — opens the breaker and the
                    # session enters device-loss recovery instead of
                    # dying (resilience/continuity).
                    _M_SUBMIT_FAIL.inc()
                    self._submit_breaker.record_failure()
                    if self._submit_breaker.state == "open":
                        log.exception(
                            "encode_submit failed %d times consecutively; "
                            "device declared lost, entering recovery",
                            self._submit_breaker.consecutive_failures)
                        obsev.emit(
                            "breaker-open",
                            session=self.journeys.session,
                            point="device-submit",
                            failures=self._submit_breaker
                            .consecutive_failures)
                        # in-flight frames died with the device; the
                        # recovery IDR is the client's next sync point
                        pending.clear()
                        self._drop_until_key = True
                        if not self._recover_device():
                            log.error("device recovery exhausted; "
                                      "stopping session")
                            return
                        continue
                    log.exception("encode_submit failed; dropping frame")
                    self._need_frame = True     # retry the capture
                    time.sleep(frame_interval)
                    continue
                self._submit_breaker.record_success()
                t_sub = time.perf_counter()
                # marks flow to the trace ring at publish; span names
                # are derived at export time (no per-frame formatting)
                pending.append((token, capture_pts, fid,
                                [("capture", t0), ("captured", t_cap),
                                 ("device-submit", t_sub)]))
                submit_ms = (t_sub - t0) * 1e3
                self._submit_ms.append(submit_ms)
                _M_SUBMIT_MS.observe(submit_ms)
                # dispatch stage (obs/budget): Python->device crossings
                # + submit-to-launch gap this frame accrued (0 crossings
                # for a ring-staged frame; the chunk's single crossing
                # lands on its dispatch frame)
                disp = self.encoder.pop_dispatch_sample() \
                    if hasattr(self.encoder, "pop_dispatch_sample") \
                    else None
                if disp is not None:
                    obsb.LEDGER.record_dispatch(disp[0], disp[1])
            # Collect the oldest frame once the pipeline is full (or the
            # source went quiet — drain so its frames aren't stranded).
            if pending and (len(pending) >= self.PIPELINE_DEPTH
                            or not changed):
                tc = time.perf_counter()
                token, frame_pts, fid, marks = pending.pop(0)
                try:
                    spec = rfaults.fire("collect_timeout")
                    if spec is not None:
                        if spec.get("mode") == "slow":
                            # sustained-budget-breach injection: inflate
                            # the collect stage without dropping frames
                            time.sleep(
                                float(spec.get("delay_ms", 50.0)) / 1e3)
                        else:
                            raise TimeoutError(
                                "fault injection: collect_timeout")
                    ef = self.encoder.encode_collect(token)
                except Exception:
                    # Transient device/transfer failure: drop this frame,
                    # keep the session alive (supervisord-style resilience).
                    # P tokens already in flight predict from a reference
                    # the client will now never decode — deliver nothing
                    # until the encoder's forced-IDR resync arrives.
                    log.exception("encode_collect failed; dropping frame")
                    _M_COLLECT_FAIL.inc()
                    self._drop_until_key = True
                    # the encoder forces its own IDR when ITS collect
                    # failed; a failure raised before reaching it (device
                    # RPC timeout, injected collect_timeout) needs the
                    # session to request the resync — idempotent either
                    # way, and rate-limited/deduped against PLI and the
                    # ladder rung (a deferred grant lands via _idr_tick)
                    self.request_idr("resync")
                    continue
                t_col = time.perf_counter()
                collect_ms = (t_col - tc) * 1e3
                self._collect_ms.append(collect_ms)
                _M_COLLECT_MS.observe(collect_ms)
                marks.append(("device-collect", t_col))
                if self._drop_until_key:
                    if not ef.keyframe:
                        continue        # stale pre-failure P frame
                    self._drop_until_key = False
                for fn in list(self._au_listeners):
                    try:
                        fn(ef.data, ef.keyframe, frame_pts)
                    except Exception:
                        log.exception("AU listener failed")
                frag = (self.muxer.fragment(ef.data, keyframe=ef.keyframe,
                                            pts_ms=frame_pts // 90)
                        if self.muxer is not None else ef.data)
                marks.append(("bitstream", time.perf_counter()))
                self.stats.record_frame(ef.encode_ms, len(frag))
                _M_FRAMES.inc()
                if ef.keyframe:
                    _M_KEYFRAMES.inc()
                _M_BYTES.inc(len(frag))
                self._post(frag, ef.keyframe, fid)
                t_pub = time.perf_counter()
                marks.append(("publish", t_pub))
                # journey: publish + the encoder's chunk/shard identity
                # (device span amortizes over the chunk at export);
                # device_ms = this frame's own submit span + collect
                jmeta = (self.encoder.pop_journey_meta()
                         if hasattr(self.encoder, "pop_journey_meta")
                         else None)
                self.journeys.complete(
                    fid, t_pub,
                    device_ms=collect_ms + (marks[2][1] - marks[1][1])
                    * 1e3,
                    meta=jmeta)
                # pts is the cross-track key: the webrtc 'rtp-sent' span
                # for this frame carries the identical pts value;
                # session/chunk/shard meta labels the Chrome-trace lane
                tmeta = [("session", self.journeys.session)]
                if jmeta and jmeta.get("chunk_len", 1) > 1:
                    tmeta += [("chunk", jmeta["chunk_id"]),
                              ("slot", jmeta["slot"])]
                if jmeta and jmeta.get("shards", 1) > 1:
                    tmeta.append(("shards", jmeta["shards"]))
                self._tracer.record_marks(fid, marks, pts=frame_pts,
                                          meta=tuple(tmeta))
                # content & quality plane (obs/content): the encoder's
                # in-graph stats for this frame, if one was sampled
                cstats = (self.encoder.pop_content_stats()
                          if hasattr(self.encoder, "pop_content_stats")
                          else None)
                if cstats is not None:
                    try:
                        from ..obs.content import PLANE as _content
                        _content.record(self.journeys.session, cstats)
                    except Exception:
                        log.exception("content stats record failed")
                self._last_tick = time.monotonic()   # delivered = progress
                # energy-proxy gauges on a ~2 s cadence at 60 fps: the
                # read is two getrusage fields, publish is two gauge sets
                self._energy_frames += 1
                if self._energy_frames >= 120:
                    try:
                        self._energy.publish(
                            self._energy_frames,
                            tune=getattr(self.encoder, "tune", "off"))
                    except Exception:
                        pass
                    self._energy.reset()
                    self._energy_frames = 0

            # continuity checkpoint on its cadence (the due-check is one
            # clock read).  Mid-pipeline state is fine: counters may run
            # a frame or two ahead of what clients saw, but restore
            # forces a recovery IDR that resets the visual chain anyway.
            self._ckpt.maybe_snapshot(self.encoder)

            elapsed = time.perf_counter() - t0
            sleep = frame_interval - elapsed
            if sleep > 0 and not self._subscribers:
                time.sleep(min(sleep * 4, 0.25))   # idle: throttle down
            elif sleep > 0:
                time.sleep(sleep)

    def _post(self, fragment: bytes, keyframe: bool,
              fid: int = 0) -> None:
        if self.loop is not None:
            self.loop.call_soon_threadsafe(self._publish, fragment,
                                           keyframe, fid)
        else:
            self._publish(fragment, keyframe, fid)

    def stats_summary(self) -> dict:
        s = self.stats.summary()
        s.update({
            "codec": self.codec_name,
            "width": self.source.width,
            "height": self.source.height,
            "clients": len(self._subscribers),
            # per-stage breakdown (SURVEY.md §5 tracing parity): submit =
            # host color conversion + async device dispatch; collect =
            # device wait + bitstream pull + assembly.
            "stage_ms": {
                "submit_p50": percentile(sorted(self._submit_ms), 50),
                "collect_p50": percentile(sorted(self._collect_ms), 50),
            },
            "continuity": {
                "recoveries": self._recoveries,
                "checkpoints": self._ckpt.count,
                "checkpoint_age_s": (None if self._ckpt.age_s is None
                                     else round(self._ckpt.age_s, 1)),
            },
        })
        return s
