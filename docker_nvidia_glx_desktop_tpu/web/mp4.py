"""First-party fragmented-MP4 (fMP4/CMAF) muxer for H.264 access units.

The reference's media packaging is GStreamer's RTP payloader feeding
webrtcbin (SURVEY.md §3.2).  Browsers can equally decode H.264 delivered as
fMP4 fragments through Media Source Extensions — which needs no GStreamer,
no SRTP stack, and rides the same WebSocket the signaling uses, so the
first-party web client plays the TPU encoder's output directly.  This
module converts Annex-B access units (what ``models/h264.py`` emits) into:

- an **init segment** (``ftyp`` + ``moov`` with ``avcC`` from the SPS/PPS and
  a ``mvex`` making it fragment-ready), and
- one **media segment** per access unit (``moof`` + ``mdat`` with
  AVCC-length-prefixed NALs), one sample per fragment for minimum latency.

Box layout follows ISO/IEC 14496-12; only what MSE requires is emitted.
"""

from __future__ import annotations

import struct
from typing import List

__all__ = ["split_annexb", "annexb_to_avcc", "Mp4Muxer"]

TIMESCALE = 90_000  # the conventional 90 kHz video clock


def _box(typ: bytes, payload: bytes) -> bytes:
    return struct.pack(">I", 8 + len(payload)) + typ + payload


def _full(typ: bytes, version: int, flags: int, payload: bytes) -> bytes:
    return _box(typ, struct.pack(">B3s", version,
                                 flags.to_bytes(3, "big")) + payload)


def split_annexb(data: bytes) -> List[bytes]:
    """Split an Annex-B byte stream into NAL units (start codes stripped).

    Handles both 3- and 4-byte start codes (the extra leading zero of a
    4-byte code belongs to the separator, not the preceding NAL).
    """
    starts = []
    pos = 0
    while True:
        pos = data.find(b"\x00\x00\x01", pos)
        if pos < 0:
            break
        starts.append(pos)
        pos += 3
    nals = []
    for idx, sc in enumerate(starts):
        begin = sc + 3
        end = starts[idx + 1] if idx + 1 < len(starts) else len(data)
        if idx + 1 < len(starts) and end > begin and data[end - 1] == 0:
            end -= 1                     # 4-byte start code's leading zero
        if end > begin:
            nals.append(data[begin:end])
    return nals


def annexb_to_avcc(data: bytes) -> bytes:
    """Annex-B AU -> AVCC (4-byte length-prefixed NALs, SPS/PPS dropped —
    they live in the init segment's avcC)."""
    out = bytearray()
    for nal in split_annexb(data):
        ntype = nal[0] & 0x1F
        if ntype in (7, 8):          # SPS/PPS carried out-of-band
            continue
        out += struct.pack(">I", len(nal)) + nal
    return bytes(out)


def _avcc_box(sps: bytes, pps: bytes) -> bytes:
    payload = struct.pack(">BBBBB", 1, sps[1], sps[2], sps[3],
                          0xFC | 3)           # lengthSizeMinusOne = 3
    payload += struct.pack(">B", 0xE0 | 1) + struct.pack(">H", len(sps)) + sps
    payload += struct.pack(">B", 1) + struct.pack(">H", len(pps)) + pps
    return _box(b"avcC", payload)


class Mp4Muxer:
    """Stateful muxer: ``init_segment()`` once, then ``fragment(au)`` per
    access unit."""

    def __init__(self, width: int, height: int, sps: bytes, pps: bytes,
                 fps: float = 60.0):
        self.width, self.height = width, height
        self.sps, self.pps = sps, pps
        self.sample_duration = int(round(TIMESCALE / fps))
        self.seq = 0
        self.decode_time = 0

    @property
    def mime(self) -> str:
        """MSE codec string from the real SPS bytes (profile_idc,
        constraint flags, level_idc)."""
        s = self.sps
        return f'video/mp4; codecs="avc1.{s[1]:02X}{s[2]:02X}{s[3]:02X}"'

    # -- init segment --------------------------------------------------

    def init_segment(self) -> bytes:
        ftyp = _box(b"ftyp", b"isom" + struct.pack(">I", 0x200)
                    + b"isomiso5iso6avc1mp41")
        return ftyp + self._moov()

    def _moov(self) -> bytes:
        mvhd = _full(b"mvhd", 0, 0, struct.pack(
            ">IIII", 0, 0, 1000, 0)                # times, timescale, dur
            + struct.pack(">iH2xII", 0x00010000, 0x0100, 0, 0)
            + struct.pack(">9i", 0x10000, 0, 0, 0, 0x10000, 0, 0, 0,
                          0x40000000)
            + b"\0" * 24 + struct.pack(">I", 2))   # pre_defined, next track
        tkhd = _full(b"tkhd", 0, 3, struct.pack(">IIII", 0, 0, 1, 0)
                     + struct.pack(">I", 0) + b"\0" * 8
                     + struct.pack(">hhhH", 0, 0, 0, 0)
                     + struct.pack(">9i", 0x10000, 0, 0, 0, 0x10000, 0, 0, 0,
                                   0x40000000)
                     + struct.pack(">II", self.width << 16,
                                   self.height << 16))
        mdhd = _full(b"mdhd", 0, 0, struct.pack(
            ">IIIIHH", 0, 0, TIMESCALE, 0, 0x55C4, 0))
        hdlr = _full(b"hdlr", 0, 0, struct.pack(">I4s", 0, b"vide")
                     + b"\0" * 12 + b"VideoHandler\0")
        vmhd = _full(b"vmhd", 0, 1, struct.pack(">HHHH", 0, 0, 0, 0))
        dref = _full(b"dref", 0, 0, struct.pack(">I", 1)
                     + _full(b"url ", 0, 1, b""))
        dinf = _box(b"dinf", dref)
        avc1 = _box(b"avc1", b"\0" * 6 + struct.pack(">H", 1)
                    + b"\0" * 16
                    + struct.pack(">HH", self.width, self.height)
                    + struct.pack(">IIIH", 0x00480000, 0x00480000, 0, 1)
                    + b"\0" * 32
                    + struct.pack(">Hh", 0x18, -1)
                    + self._avcc())
        stsd = _full(b"stsd", 0, 0, struct.pack(">I", 1) + avc1)
        stbl = _box(b"stbl", stsd
                    + _full(b"stts", 0, 0, struct.pack(">I", 0))
                    + _full(b"stsc", 0, 0, struct.pack(">I", 0))
                    + _full(b"stsz", 0, 0, struct.pack(">II", 0, 0))
                    + _full(b"stco", 0, 0, struct.pack(">I", 0)))
        minf = _box(b"minf", vmhd + dinf + stbl)
        mdia = _box(b"mdia", mdhd + hdlr + minf)
        trak = _box(b"trak", tkhd + mdia)
        trex = _full(b"trex", 0, 0, struct.pack(">IIIII", 1, 1, 0, 0, 0))
        mvex = _box(b"mvex", trex)
        return _box(b"moov", mvhd + trak + mvex)

    def _avcc(self) -> bytes:
        return _avcc_box(self.sps, self.pps)

    # -- media segments ------------------------------------------------

    def fragment(self, annexb_au: bytes, keyframe: bool = True,
                 pts_ms: int = None) -> bytes:
        """One moof+mdat for one access unit.

        ``pts_ms`` is accepted for muxer-interface uniformity and ignored:
        the MSE client plays this stream in 'sequence' mode, where append
        order defines the timeline."""
        payload = annexb_to_avcc(annexb_au)
        self.seq += 1
        mfhd = _full(b"mfhd", 0, 0, struct.pack(">I", self.seq))
        # tfhd: default-base-is-moof (0x20000) + default sample duration
        # (0x8) + default sample flags (0x20).
        nonsync = 0x0101_0000          # sample_depends_on=1, non-sync
        sync = 0x0200_0000             # sample_depends_on=2... sync sample
        tfhd = _full(b"tfhd", 0, 0x20000 | 0x8 | 0x20,
                     struct.pack(">III", 1, self.sample_duration,
                                 sync if keyframe else nonsync))
        tfdt = _full(b"tfdt", 1, 0, struct.pack(">Q", self.decode_time))
        self.decode_time += self.sample_duration
        # trun: data-offset (0x1) + sample-size (0x200); one sample.  The
        # data_offset (moof start -> mdat payload) is fully determined by
        # the box sizes: moof hdr + mfhd + traf hdr + tfhd + tfdt + trun
        # (trun = 8 hdr + 4 ver/flags + 4 count + 4 offset + 4 size = 24),
        # plus the mdat header.
        trun_len = 24
        moof_len = 8 + len(mfhd) + 8 + len(tfhd) + len(tfdt) + trun_len
        data_offset = moof_len + 8
        trun = _full(b"trun", 0, 0x1 | 0x200,
                     struct.pack(">IiI", 1, data_offset, len(payload)))
        traf = _box(b"traf", tfhd + tfdt + trun)
        moof = _box(b"moof", mfhd + traf)
        assert len(moof) == moof_len
        return moof + _box(b"mdat", payload)
