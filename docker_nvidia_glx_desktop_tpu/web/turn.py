"""TURN/STUN credential plumbing — NAT traversal parity.

The reference passes TURN config through env vars into selkies
(xgl.yml:85-109, README.md:65-143): either long-term credentials
(TURN_USERNAME/TURN_PASSWORD) or a shared secret (TURN_SHARED_SECRET) from
which per-session ephemeral credentials are derived using the TURN REST API
convention (username = "<expiry>:<user>", password =
base64(HMAC-SHA1(secret, username)) — the coturn ``use-auth-secret``
scheme).  The web client fetches this as an RTCConfiguration-shaped JSON.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import time
from typing import Optional

from ..utils.config import Config

__all__ = ["rest_credentials", "ice_servers", "server_turn_config"]

DEFAULT_STUN = "stun:stun.l.google.com:19302"


def rest_credentials(shared_secret: str, user: str = "tpu-desktop",
                     ttl_s: int = 86400, now: Optional[float] = None) -> dict:
    """coturn REST-API ephemeral credentials from a shared secret."""
    expiry = int((time.time() if now is None else now) + ttl_s)
    username = f"{expiry}:{user}"
    digest = hmac.new(shared_secret.encode(), username.encode(),
                      hashlib.sha1).digest()
    return {"username": username,
            "credential": base64.b64encode(digest).decode()}


def server_turn_config(cfg: Config) -> Optional[dict]:
    """TURN parameters for the SERVER's own allocation
    (webrtc/turn_client) — the reference relays the server's media via
    webrtcbin's TURN config when hostNetwork is impossible
    (README.md:65-69).  None when TURN is unconfigured or the transport
    is one the first-party client doesn't speak (UDP only)."""
    if not cfg.turn_host:
        return None
    if cfg.turn_protocol not in ("", None, "udp") or cfg.turn_tls:
        import logging
        logging.getLogger(__name__).warning(
            "TURN_PROTOCOL=%s/TLS=%s: server-side relay speaks UDP only; "
            "clients still receive these credentials via /turn",
            cfg.turn_protocol, cfg.turn_tls)
        return None
    if cfg.turn_shared_secret:
        creds = rest_credentials(cfg.turn_shared_secret)
    elif cfg.turn_username:
        creds = {"username": cfg.turn_username,
                 "credential": cfg.turn_password}
    else:
        return None
    return {"host": cfg.turn_host, "port": int(cfg.turn_port or 3478),
            **creds}


def ice_servers(cfg: Config, now: Optional[float] = None) -> dict:
    """RTCConfiguration fragment for the web client (iceServers list)."""
    servers = [{"urls": [DEFAULT_STUN]}]
    if cfg.turn_host:
        scheme = "turns" if cfg.turn_tls else "turn"
        transport = cfg.turn_protocol if cfg.turn_protocol in ("udp", "tcp") \
            else "udp"
        url = (f"{scheme}:{cfg.turn_host}:{cfg.turn_port}"
               f"?transport={transport}")
        entry: dict = {"urls": [url]}
        if cfg.turn_shared_secret:
            entry.update(rest_credentials(cfg.turn_shared_secret, now=now))
        elif cfg.turn_username:
            entry.update({"username": cfg.turn_username,
                          "credential": cfg.turn_password})
        servers.append(entry)
    return {"iceServers": servers}
