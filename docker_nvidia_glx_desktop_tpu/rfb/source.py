"""Frame sources: where pixels come from.

The reference scrapes the X display (x11vnc -snapfb over XSHM,
entrypoint.sh:123; GStreamer ximagesrc for WebRTC, SURVEY.md §3.2).  Here the
capture surface is an abstraction so every consumer (RFB server, MSE/WebRTC
streamer, batch encoder) is testable without an X server:

- :class:`SyntheticSource` — deterministic moving desktop-like test pattern.
- :class:`NumpySource`    — push frames from code (session manager, tests).
- :class:`XShmSource`     — real X display capture via a small C shim
  (``native/xcapture.cpp``, XGetImage/XShmGetImage), compiled on demand and
  only importable where Xlib headers/libs exist (the container image).

All sources yield ``(H, W, 3) uint8`` RGB plus a monotonically increasing
damage sequence number so pull-based consumers can skip unchanged frames.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

import numpy as np

__all__ = ["FrameSource", "SyntheticSource", "NumpySource", "make_source"]


class FrameSource:
    """Interface: latest-frame semantics (lossy, like a framebuffer)."""

    width: int
    height: int

    def frame(self) -> Tuple[np.ndarray, int]:
        """Return (rgb, seq). seq increments whenever content changed."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class SyntheticSource(FrameSource):
    """Deterministic desktop-ish pattern with motion: gradient background,
    a 'window' rectangle and a scrolling 'text' band (matches the bench
    frame mix so measured numbers line up)."""

    def __init__(self, width: int = 640, height: int = 480, fps: float = 60.0):
        self.width, self.height = width, height
        self._fps = fps
        self._t0 = time.monotonic()
        yy, xx = np.mgrid[0:height, 0:width]
        self._base = np.stack(
            [(xx * 255 // max(width - 1, 1)).astype(np.uint8),
             (yy * 255 // max(height - 1, 1)).astype(np.uint8),
             ((xx + yy) * 255 // max(height + width - 2, 1)).astype(np.uint8)],
            axis=-1)
        rng = np.random.default_rng(0)
        self._band = (rng.integers(0, 2, size=(max(height // 8, 1), width, 3))
                      * 200).astype(np.uint8)

    def frame(self) -> Tuple[np.ndarray, int]:
        seq = int((time.monotonic() - self._t0) * self._fps)
        f = self._base.copy()
        h, w = self.height, self.width
        # moving window
        x0 = (seq * 4) % max(w // 2, 1)
        f[h // 4:h // 2, x0:min(x0 + w // 4, w)] = (240, 240, 235)
        # scrolling text band
        band = np.roll(self._band, seq * 2, axis=1)
        f[h // 2:h // 2 + band.shape[0]] = band
        return f, seq

    def resize(self, width: int, height: int) -> None:
        """Dynamic-resolution support (WEBRTC_ENABLE_RESIZE)."""
        self.__init__(width, height, fps=self._fps)


class NumpySource(FrameSource):
    """Thread-safe push source: ``push(frame)`` makes it the current frame."""

    def __init__(self, width: int, height: int):
        self.width, self.height = width, height
        self._lock = threading.Lock()
        self._frame = np.zeros((height, width, 3), np.uint8)
        self._seq = 0

    def push(self, rgb: np.ndarray) -> None:
        if rgb.shape != (self.height, self.width, 3):
            raise ValueError(f"frame shape {rgb.shape} != "
                             f"({self.height}, {self.width}, 3)")
        with self._lock:
            self._frame = np.ascontiguousarray(rgb, dtype=np.uint8)
            self._seq += 1

    def frame(self) -> Tuple[np.ndarray, int]:
        with self._lock:
            return self._frame, self._seq


class XShmSource(FrameSource):
    """X display capture through the native shim (container runtime only)."""

    def __init__(self, display: str = ":0"):
        from ..native import lib as native_lib
        self._display = display
        self._cap = native_lib.open_xcapture(display)
        if self._cap is None:
            raise RuntimeError(
                f"cannot open X display {display!r} (no X server or the "
                "xcapture shim is unavailable on this host)")
        self.width, self.height = self._cap.size()
        self._seq = 0
        self._copy: Optional[np.ndarray] = None
        self._grab_t = 0.0

    # Minimum wall time between real grabs: bounds the damage-compare
    # cost no matter how fast pollers (encode loop + N RFB clients) spin.
    MIN_GRAB_INTERVAL_S = 0.008

    def frame(self) -> Tuple[np.ndarray, int]:
        # The shim returns its one shared XShm buffer, overwritten by the
        # next grab while up to PIPELINE_DEPTH frames may still be in
        # flight in the encoder — so changed frames are copied out, and
        # the damage seq only advances when content actually changed
        # (exact compare, ~2-3 ms at 1080p): an idle desktop is not
        # re-encoded at full rate.
        now = time.monotonic()
        if (self._copy is not None
                and now - self._grab_t < self.MIN_GRAB_INTERVAL_S):
            return self._copy, self._seq
        self._grab_t = now
        raw = self._cap.grab()
        if self._copy is None or not np.array_equal(raw, self._copy):
            self._seq += 1
            self._copy = raw.copy()
        return self._copy, self._seq

    def resize(self, width: int, height: int) -> None:
        """Resize the X display via xrandr (reference WEBRTC_ENABLE_RESIZE
        backend, Dockerfile:211/419-431) and re-open the capture."""
        import shutil
        import subprocess

        if shutil.which("xrandr") is None:
            raise RuntimeError("xrandr not installed")
        subprocess.run(["xrandr", "--fb", f"{width}x{height}"],
                       env={"DISPLAY": self._display}, timeout=10,
                       check=True, capture_output=True)
        self._cap.close()
        from ..native import lib as native_lib
        self._cap = native_lib.open_xcapture(self._display)
        if self._cap is None:
            raise RuntimeError("re-opening X capture after resize failed")
        self.width, self.height = self._cap.size()

    def close(self) -> None:
        self._cap.close()


def make_source(display: Optional[str], width: int, height: int) -> FrameSource:
    """Real X capture when a display exists, synthetic otherwise."""
    if display:
        import os

        from ..platform.xwait import x_socket_path
        if os.path.exists(x_socket_path(display)):
            try:
                return XShmSource(display)
            except Exception:
                pass
    return SyntheticSource(width, height)
