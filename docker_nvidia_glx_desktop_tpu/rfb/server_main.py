"""CLI entry for the first-party RFB server (the ``x11vnc`` program slot in
the boot plan, entrypoint.sh:123): serve the configured X display — or the
synthetic source when no display exists — on RFB port 5900 with
``BASIC_AUTH_PASSWORD``/``NOVNC_VIEWPASS`` password semantics."""

from __future__ import annotations

import asyncio
import logging

from ..utils.config import from_env
from .server import RfbServer
from .source import make_source

RFB_PORT = 5900


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    cfg = from_env()
    source = make_source(cfg.display, cfg.sizew, cfg.sizeh)

    on_input = None
    try:
        from ..web.input import make_injector
        on_input = make_injector(cfg.display).handle_rfb
    except Exception:
        logging.exception("no input injector; view-only session")

    server = RfbServer(source=source,
                       password=cfg.effective_basic_auth_password,
                       viewpass=cfg.novnc_viewpass,
                       on_input=on_input)

    async def run():
        await server.start("0.0.0.0", RFB_PORT)
        logging.info("rfb server on :%d (%dx%d)", RFB_PORT,
                     source.width, source.height)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
