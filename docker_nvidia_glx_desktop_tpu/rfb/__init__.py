"""First-party RFB (VNC) stack — the ``x11vnc`` + ``websockify`` fallback
path (reference entrypoint.sh:120-125) reimplemented so the noVNC rung of
the BASELINE ladder works even on hosts with no X/VNC packages at all."""

from .source import FrameSource, SyntheticSource, NumpySource  # noqa: F401
