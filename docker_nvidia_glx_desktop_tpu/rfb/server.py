"""First-party RFB 3.8 server — the ``x11vnc`` role (entrypoint.sh:123).

Implements the protocol subset every mainstream viewer (noVNC, TigerVNC,
RealVNC) negotiates:

- protocol 3.8 handshake, security None / VNC Authentication (DES challenge,
  ``rfb/des.py``), with x11vnc's ``-passwd``/``-viewpasswd`` semantics
  (full-control vs view-only password, entrypoint.sh:122);
- ServerInit with true-color RGB888; SetPixelFormat honored for 32/16 bpp
  true-color formats;
- FramebufferUpdate with **Raw** and **Tight-JPEG** rectangles.  Tight JPEG
  frames come from the TPU MJPEG encoder (``models/mjpeg.py``) — the
  fallback path's pixels ride the same accelerator as the WebRTC path,
  which is the whole point of the rebuild (the reference's fallback is
  CPU-only, README.md:15);
- KeyEvent / PointerEvent forwarded to an injectable input callback
  (``web/input.py`` backends); ClientCutText accepted.

Demand-driven updates per RFC 6143 §7.5.3: one FramebufferUpdate per
FramebufferUpdateRequest, throttled to ``max_fps``.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
from typing import Callable, Optional

import numpy as np

from ..obs import metrics as obsm
from . import des
from .source import FrameSource, SyntheticSource

log = logging.getLogger(__name__)

__all__ = ["RfbServer", "PixelFormat"]

_M_UPDATES = obsm.counter(
    "dngd_rfb_updates_total",
    "FramebufferUpdate messages sent", ("encoding",))
_M_UPDATE_BYTES = obsm.counter(
    "dngd_rfb_update_bytes_total",
    "FramebufferUpdate bytes sent (all encodings)")
_M_UPDATES_TIGHT = _M_UPDATES.labels("tight")
_M_UPDATES_RAW = _M_UPDATES.labels("raw")
_M_CLIENTS = obsm.gauge("dngd_rfb_clients", "Connected RFB clients")
_M_JPEG_MS = obsm.histogram(
    "dngd_rfb_jpeg_encode_ms",
    "Tight-JPEG rect encode time (TPU MJPEG path or cv2 fallback)")

ENC_RAW = 0
ENC_TIGHT = 7
ENC_DESKTOP_SIZE = -223


class PixelFormat:
    """Client pixel format (RFC 6143 §7.4)."""

    def __init__(self, bpp=32, depth=24, big_endian=0, true_color=1,
                 rmax=255, gmax=255, bmax=255, rshift=16, gshift=8, bshift=0):
        self.bpp, self.depth = bpp, depth
        self.big_endian, self.true_color = big_endian, true_color
        self.rmax, self.gmax, self.bmax = rmax, gmax, bmax
        self.rshift, self.gshift, self.bshift = rshift, gshift, bshift

    def pack(self) -> bytes:
        return struct.pack(">BBBBHHHBBB3x", self.bpp, self.depth,
                           self.big_endian, self.true_color,
                           self.rmax, self.gmax, self.bmax,
                           self.rshift, self.gshift, self.bshift)

    @classmethod
    def unpack(cls, raw: bytes) -> "PixelFormat":
        f = struct.unpack(">BBBBHHHBBB3x", raw)
        return cls(*f)

    def encode_rgb(self, rgb: np.ndarray) -> bytes:
        """(H, W, 3) uint8 -> raw bytes in this pixel format."""
        r = rgb[..., 0].astype(np.uint32)
        g = rgb[..., 1].astype(np.uint32)
        b = rgb[..., 2].astype(np.uint32)
        if self.true_color:
            r = (r * self.rmax // 255) << self.rshift
            g = (g * self.gmax // 255) << self.gshift
            b = (b * self.bmax // 255) << self.bshift
        px = r | g | b
        order = ">" if self.big_endian else "<"
        if self.bpp == 32:
            return px.astype(f"{order}u4").tobytes()
        if self.bpp == 16:
            return px.astype(f"{order}u2").tobytes()
        if self.bpp == 8:
            return px.astype(np.uint8).tobytes()
        raise ValueError(f"unsupported bpp {self.bpp}")


def _tight_compact_len(n: int) -> bytes:
    """Tight encoding's 1-3 byte compact length."""
    out = bytearray([n & 0x7F])
    n >>= 7
    if n:
        out[0] |= 0x80
        out.append(n & 0x7F)
        n >>= 7
        if n:
            out[1] |= 0x80
            out.append(n & 0xFF)
    return bytes(out)


class _Client:
    def __init__(self, reader, writer):
        self.reader, self.writer = reader, writer
        self.pixfmt = PixelFormat()
        self.encodings: list = []
        self.view_only = False
        self.pending_request: Optional[tuple] = None
        self.last_seq = -1

    @property
    def wants_tight(self) -> bool:
        return ENC_TIGHT in self.encodings and self.pixfmt.bpp in (16, 32)


class RfbServer:
    """Serve a :class:`FrameSource` over RFB."""

    def __init__(self, source: Optional[FrameSource] = None,
                 password: str = "", viewpass: str = "",
                 name: str = "tpu-desktop", max_fps: float = 30.0,
                 jpeg_quality: int = 75, use_tpu_jpeg: bool = True,
                 on_input: Optional[Callable[[dict], None]] = None):
        self.source = source or SyntheticSource()
        self.password = password
        self.viewpass = viewpass
        self.name = name
        self.max_fps = max_fps
        self.jpeg_quality = jpeg_quality
        self.use_tpu_jpeg = use_tpu_jpeg
        self.on_input = on_input
        self._jpeg_enc = None
        self._server: Optional[asyncio.AbstractServer] = None
        self.clients: list = []

    # -- lifecycle -----------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 5900):
        self._server = await asyncio.start_server(self._handle, host, port)
        return self._server

    async def close(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    @property
    def port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    # -- handshake -----------------------------------------------------

    async def _handle(self, reader, writer):
        c = _Client(reader, writer)
        try:
            await self._handshake(c)
            self.clients.append(c)
            _M_CLIENTS.set(len(self.clients))
            await self._message_loop(c)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception:
            log.exception("rfb client error")
        finally:
            if c in self.clients:
                self.clients.remove(c)
            _M_CLIENTS.set(len(self.clients))
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    async def _handshake(self, c: _Client):
        c.writer.write(b"RFB 003.008\n")
        await c.writer.drain()
        ver = await c.reader.readexactly(12)
        if not ver.startswith(b"RFB "):
            raise ConnectionError("bad version string")

        if self.password:
            c.writer.write(bytes([1, 2]))          # one type: VNC auth
            await c.writer.drain()
            if (await c.reader.readexactly(1))[0] != 2:
                raise ConnectionError("client refused VNC auth")
            challenge = des.new_challenge()
            c.writer.write(challenge)
            await c.writer.drain()
            response = await c.reader.readexactly(16)
            if des.vnc_check_response(self.password, challenge, response):
                c.view_only = False
            elif self.viewpass and des.vnc_check_response(
                    self.viewpass, challenge, response):
                c.view_only = True                 # x11vnc -viewpasswd
            else:
                c.writer.write(struct.pack(">I", 1))
                reason = b"authentication failed"
                c.writer.write(struct.pack(">I", len(reason)) + reason)
                await c.writer.drain()
                raise ConnectionError("auth failed")
        else:
            c.writer.write(bytes([1, 1]))          # one type: None
            await c.writer.drain()
            if (await c.reader.readexactly(1))[0] != 1:
                raise ConnectionError("client refused security none")
        c.writer.write(struct.pack(">I", 0))       # SecurityResult OK
        await c.writer.drain()

        await c.reader.readexactly(1)              # ClientInit (shared flag)
        name = self.name.encode()
        c.writer.write(struct.pack(">HH", self.source.width,
                                   self.source.height)
                       + c.pixfmt.pack()
                       + struct.pack(">I", len(name)) + name)
        await c.writer.drain()

    # -- message loop --------------------------------------------------

    async def _message_loop(self, c: _Client):
        interval = 1.0 / self.max_fps
        while True:
            try:
                hdr = await asyncio.wait_for(c.reader.readexactly(1), interval)
            except asyncio.TimeoutError:
                await self._maybe_update(c)
                continue
            mtype = hdr[0]
            if mtype == 0:                          # SetPixelFormat
                raw = await c.reader.readexactly(19)
                fmt = PixelFormat.unpack(raw[3:])
                if not fmt.true_color:
                    # Palette (colour-map) formats would be silently
                    # mis-encoded through the true-color path; refuse
                    # explicitly rather than corrupt the display.
                    log.warning("client requested palette pixel format; "
                                "only true-color is served — disconnecting")
                    raise ConnectionError("non-true-color pixel format")
                c.pixfmt = fmt
            elif mtype == 2:                        # SetEncodings
                _, n = struct.unpack(">xH", await c.reader.readexactly(3))
                raw = await c.reader.readexactly(4 * n)
                c.encodings = list(struct.unpack(f">{n}i", raw))
            elif mtype == 3:                        # FramebufferUpdateRequest
                inc, x, y, w, h = struct.unpack(
                    ">BHHHH", await c.reader.readexactly(9))
                c.pending_request = (inc, x, y, w, h)
                if not inc:
                    c.last_seq = -1                 # force a full send
                await self._maybe_update(c)
            elif mtype == 4:                        # KeyEvent
                down, _, key = struct.unpack(
                    ">BHI", await c.reader.readexactly(7))
                self._input(c, {"type": "key", "down": bool(down),
                                "keysym": key})
            elif mtype == 5:                        # PointerEvent
                mask, x, y = struct.unpack(
                    ">BHH", await c.reader.readexactly(5))
                self._input(c, {"type": "pointer", "buttons": mask,
                                "x": x, "y": y})
            elif mtype == 6:                        # ClientCutText
                (ln,) = struct.unpack(">3xI", await c.reader.readexactly(7))
                text = await c.reader.readexactly(ln)
                self._input(c, {"type": "cuttext",
                                "text": text.decode("latin-1")})
            else:
                raise ConnectionError(f"unknown client message {mtype}")

    def _input(self, c: _Client, event: dict) -> None:
        if c.view_only or self.on_input is None:
            return
        try:
            self.on_input(event)
        except Exception:
            log.exception("input callback failed")

    # -- framebuffer updates -------------------------------------------

    async def _maybe_update(self, c: _Client):
        if c.pending_request is None:
            return
        rgb, seq = self.source.frame()
        if seq == c.last_seq:
            return
        c.last_seq = seq
        _, x, y, w, h = c.pending_request
        c.pending_request = None
        await self._send_update(c, rgb, (x, y, w, h))

    async def _send_update(self, c: _Client, rgb: np.ndarray,
                           req: Optional[tuple] = None):
        fh, fw = rgb.shape[:2]
        x0, y0, rw, rh = req if req is not None else (0, 0, fw, fh)
        x0, y0 = min(x0, fw), min(y0, fh)
        rw, rh = min(rw, fw - x0), min(rh, fh - y0)
        if rw <= 0 or rh <= 0:                      # degenerate request
            x0, y0, rw, rh = 0, 0, fw, fh
        full = (x0, y0, rw, rh) == (0, 0, fw, fh)
        # Tight-JPEG stays full-frame (the TPU JPEG kernel is specialized
        # per geometry, and noVNC always asks full-frame); a partial
        # request is honored with a Raw rect clamped to the asked area
        # (RFC 6143 §7.5.3).
        data = self._jpeg(rgb) if (full and c.wants_tight) else None
        if data is not None:
            rect = struct.pack(">HHHHi", 0, 0, fw, fh, ENC_TIGHT)
            payload = bytes([0x90]) + _tight_compact_len(len(data)) + data
            msg = struct.pack(">BxH", 0, 1) + rect + payload
            _M_UPDATES_TIGHT.inc()
        else:
            sub = rgb[y0:y0 + rh, x0:x0 + rw]
            rect = struct.pack(">HHHHi", x0, y0, rw, rh, ENC_RAW)
            msg = (struct.pack(">BxH", 0, 1) + rect
                   + c.pixfmt.encode_rgb(sub))
            _M_UPDATES_RAW.inc()
        _M_UPDATE_BYTES.inc(len(msg))
        c.writer.write(msg)
        await c.writer.drain()

    def _jpeg(self, rgb: np.ndarray) -> Optional[bytes]:
        """JPEG bytes for a Tight rect — TPU MJPEG encoder preferred."""
        t0 = time.perf_counter()
        try:
            return self._jpeg_inner(rgb)
        finally:
            _M_JPEG_MS.observe((time.perf_counter() - t0) * 1e3)

    def _jpeg_inner(self, rgb: np.ndarray) -> Optional[bytes]:
        h, w = rgb.shape[:2]
        if self.use_tpu_jpeg:
            try:
                if (self._jpeg_enc is None
                        or self._jpeg_enc.width != w
                        or self._jpeg_enc.height != h):
                    from ..models.mjpeg import JpegEncoder
                    self._jpeg_enc = JpegEncoder(
                        w, h, quality=self.jpeg_quality)
                return self._jpeg_enc.encode(rgb).data
            except Exception:
                log.exception("TPU JPEG failed; falling back to cv2")
                self.use_tpu_jpeg = False
        try:
            import cv2
            ok, buf = cv2.imencode(
                ".jpg", rgb[:, :, ::-1],
                [cv2.IMWRITE_JPEG_QUALITY, self.jpeg_quality])
            return buf.tobytes() if ok else None
        except Exception:
            return None
