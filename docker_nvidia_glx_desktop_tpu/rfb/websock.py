"""WebSocket <-> TCP bridge — the ``websockify`` role (entrypoint.sh:124,
reference Dockerfile:506-510).

noVNC speaks RFB over a binary WebSocket; websockify splices that onto the
TCP RFB port and serves the noVNC web app on the same port (the reference
symlinks index.html -> vnc.html, Dockerfile:508).  Same contract here as one
aiohttp application: WebSocket upgrades anywhere on the port bridge to RFB,
plain GETs serve the noVNC distribution directory when present (correct
Content-Type, query strings ignored, no path escapes) or a status page.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from aiohttp import WSMsgType, web

from ..obs.http import add_obs_routes

log = logging.getLogger(__name__)

__all__ = ["make_app", "serve_bridge", "main"]

HEARTBEAT_S = 10.0  # novnc_proxy --heartbeat 10 (entrypoint.sh:124)


async def _bridge(ws: web.WebSocketResponse, tcp_host: str,
                  tcp_port: int) -> None:
    try:
        reader, writer = await asyncio.open_connection(tcp_host, tcp_port)
    except OSError as e:
        log.warning("bridge: cannot reach %s:%d: %s", tcp_host, tcp_port, e)
        await ws.close(code=1011, message=b"backend unreachable")
        return

    async def tcp_to_ws():
        while True:
            data = await reader.read(65536)
            if not data:
                break
            await ws.send_bytes(data)
        await ws.close()

    pump = asyncio.ensure_future(tcp_to_ws())
    try:
        async for msg in ws:
            if msg.type == WSMsgType.BINARY:
                writer.write(msg.data)
                await writer.drain()
            elif msg.type == WSMsgType.TEXT:
                writer.write(msg.data.encode())
                await writer.drain()
            elif msg.type in (WSMsgType.CLOSE, WSMsgType.ERROR):
                break
    finally:
        pump.cancel()
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass


def make_app(tcp_host: str = "127.0.0.1", tcp_port: int = 5900,
             web_root: Optional[str] = None) -> web.Application:
    app = web.Application()

    async def entry(request: web.Request):
        if request.headers.get("Upgrade", "").lower() == "websocket":
            ws = web.WebSocketResponse(heartbeat=HEARTBEAT_S, protocols=("binary",))
            await ws.prepare(request)
            await _bridge(ws, tcp_host, tcp_port)
            return ws
        if web_root:
            return web.HTTPFound("/app/index.html")
        return web.Response(
            text="tpu-desktop websocket bridge: connect a WebSocket "
                 "(noVNC/RFB) to this port\n")

    app.router.add_get("/", entry)
    app.router.add_get("/websockify", entry)
    # same telemetry surface as the streaming web server: the rfb/noVNC
    # fallback port is scrapeable on its own when it runs standalone
    add_obs_routes(app)

    if web_root:
        # aiohttp's static handler: correct Content-Type, traversal-safe.
        app.router.add_static("/app/", web_root, follow_symlinks=True)
    return app


async def serve_bridge(listen_host: str, listen_port: int,
                       tcp_host: str = "127.0.0.1", tcp_port: int = 5900,
                       web_root: Optional[str] = None) -> web.AppRunner:
    """Start the bridge; returns the AppRunner (``.addresses`` has the
    bound port; ``await runner.cleanup()`` stops it)."""
    runner = web.AppRunner(make_app(tcp_host, tcp_port, web_root))
    await runner.setup()
    site = web.TCPSite(runner, listen_host, listen_port)
    await site.start()
    return runner


def bound_port(runner: web.AppRunner) -> int:
    for site in runner.sites:
        server = site._server  # noqa: SLF001 — aiohttp exposes no public port
        if server and server.sockets:
            return server.sockets[0].getsockname()[1]
    raise RuntimeError("bridge not bound")


def main() -> None:
    import os

    from ..utils.config import from_env

    cfg = from_env()
    web_root = next((p for p in ("/opt/noVNC", "/usr/share/novnc")
                     if os.path.isdir(p)), None)

    async def run():
        runner = await serve_bridge(cfg.listen_addr, cfg.listen_port,
                                    "127.0.0.1", 5900, web_root)
        log.info("websock bridge on %s:%d -> 127.0.0.1:5900",
                 cfg.listen_addr, cfg.listen_port)
        try:
            await asyncio.Event().wait()
        finally:
            await runner.cleanup()

    asyncio.run(run())


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    main()
