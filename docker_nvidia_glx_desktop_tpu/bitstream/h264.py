"""H.264 (ISO/IEC 14496-10) bitstream syntax: Exp-Golomb, NAL wrapping,
SPS/PPS/slice headers.

This replaces the bitstream-construction half of the reference's
``nvh264enc`` element (reference Dockerfile:210): NVENC emits Annex-B NAL
units in silicon; we emit them first-party.  Only baseline-profile intra
tools are produced initially (CAVLC, I-slices), matching the reference's
``WEBRTC_ENCODER`` default envelope of constrained-baseline H.264
(README.md:19-21).
"""

from __future__ import annotations

from .bitwriter import BitWriter


# ---------------------------------------------------------------------------
# Exp-Golomb
# ---------------------------------------------------------------------------

def write_ue(bw: BitWriter, v: int) -> None:
    """Unsigned Exp-Golomb code."""
    assert v >= 0
    code = v + 1
    nbits = code.bit_length()
    bw.write(0, nbits - 1)
    bw.write(code, nbits)


def write_se(bw: BitWriter, v: int) -> None:
    """Signed Exp-Golomb: 0, 1, -1, 2, -2 ... -> ue(0), ue(1), ue(2) ..."""
    write_ue(bw, 2 * v - 1 if v > 0 else -2 * v)


def rbsp_trailing_bits(bw: BitWriter) -> None:
    bw.write(1, 1)
    bw.pad_to_byte(0)


# ---------------------------------------------------------------------------
# NAL units
# ---------------------------------------------------------------------------

NAL_SLICE = 1
NAL_IDR = 5
NAL_SEI = 6
NAL_SPS = 7
NAL_PPS = 8

START_CODE = b"\x00\x00\x00\x01"


def emulation_prevention(rbsp: bytes) -> bytes:
    """Insert 0x03 after any 0x0000 followed by 0x00/01/02/03 (spec §7.4.1.1)."""
    out = bytearray()
    zeros = 0
    for b in rbsp:
        if zeros >= 2 and b <= 3:
            out.append(3)
            zeros = 0
        out.append(b)
        zeros = zeros + 1 if b == 0 else 0
    return bytes(out)


def nal_unit(nal_type: int, rbsp: bytes, ref_idc: int = 3) -> bytes:
    """Annex-B NAL unit: start code + header byte + EPB-escaped RBSP."""
    from ..native import lib as native_lib
    header = bytes([(ref_idc << 5) | nal_type])
    if len(rbsp) > 4096 and native_lib.available():
        escaped = native_lib.emulation_prevention(rbsp)
    else:
        escaped = emulation_prevention(rbsp)
    return START_CODE + header + escaped


# ---------------------------------------------------------------------------
# Parameter sets (baseline profile)
# ---------------------------------------------------------------------------

def sps_rbsp(width: int, height: int, level_idc: int = 42,
             profile: str = "baseline") -> bytes:
    """Sequence parameter set for progressive 4:2:0.

    ``profile``: "baseline" (CAVLC streams) or "main" (required for
    CABAC, spec A.2.2 — baseline excludes entropy_coding_mode_flag=1).
    Frame cropping carries non-multiple-of-16 dimensions; POC type 2 keeps
    the slice header free of POC syntax for an I/P-only stream.
    """
    mb_w = (width + 15) // 16
    mb_h = (height + 15) // 16
    crop_r = mb_w * 16 - width      # luma samples to crop on the right
    crop_b = mb_h * 16 - height     # and bottom
    bw = BitWriter()
    if profile == "main":
        bw.write(77, 8)              # profile_idc: main
        bw.write(0b01000000, 8)      # constraint_set1 (main), reserved 0
    else:
        bw.write(66, 8)              # profile_idc: baseline
        bw.write(0b11000000, 8)      # constraint_set0+1, reserved zeros
    bw.write(level_idc, 8)
    write_ue(bw, 0)                  # seq_parameter_set_id
    write_ue(bw, 0)                  # log2_max_frame_num_minus4 -> 4 bits
    write_ue(bw, 2)                  # pic_order_cnt_type
    write_ue(bw, 1)                  # max_num_ref_frames
    bw.write(0, 1)                   # gaps_in_frame_num_value_allowed
    write_ue(bw, mb_w - 1)           # pic_width_in_mbs_minus1
    write_ue(bw, mb_h - 1)           # pic_height_in_map_units_minus1
    bw.write(1, 1)                   # frame_mbs_only_flag
    bw.write(1, 1)                   # direct_8x8_inference_flag
    if crop_r or crop_b:
        bw.write(1, 1)               # frame_cropping_flag
        write_ue(bw, 0)              # left (chroma units: /2)
        write_ue(bw, crop_r // 2)    # right
        write_ue(bw, 0)              # top
        write_ue(bw, crop_b // 2)    # bottom
    else:
        bw.write(0, 1)
    bw.write(0, 1)                   # vui_parameters_present_flag
    rbsp_trailing_bits(bw)
    return bw.getvalue()


def pps_rbsp(init_qp: int = 26, cabac: bool = False) -> bytes:
    """Picture parameter set: CAVLC or CABAC entropy coding.

    deblocking_filter_control_present_flag=1 lets every slice header turn
    the loop filter off (disable_deblocking_filter_idc=1), which our
    parallel closed-loop reconstruction requires to stay bit-exact.
    """
    bw = BitWriter()
    write_ue(bw, 0)                  # pic_parameter_set_id
    write_ue(bw, 0)                  # seq_parameter_set_id
    bw.write(1 if cabac else 0, 1)   # entropy_coding_mode_flag
    bw.write(0, 1)                   # bottom_field_pic_order_in_frame_present
    write_ue(bw, 0)                  # num_slice_groups_minus1
    write_ue(bw, 0)                  # num_ref_idx_l0_default_active_minus1
    write_ue(bw, 0)                  # num_ref_idx_l1_default_active_minus1
    bw.write(0, 1)                   # weighted_pred_flag
    bw.write(0, 2)                   # weighted_bipred_idc
    write_se(bw, init_qp - 26)       # pic_init_qp_minus26
    write_se(bw, 0)                  # pic_init_qs_minus26
    write_se(bw, 0)                  # chroma_qp_index_offset
    bw.write(1, 1)                   # deblocking_filter_control_present_flag
    bw.write(0, 1)                   # constrained_intra_pred_flag
    bw.write(0, 1)                   # redundant_pic_cnt_present_flag
    rbsp_trailing_bits(bw)
    return bw.getvalue()


def slice_header(bw: BitWriter, *, first_mb: int, slice_type: int,
                 frame_num: int, idr: bool, idr_pic_id: int = 0,
                 qp_delta: int = 0, deblocking_idc: int = 1,
                 cabac: bool = False, cabac_init_idc: int = 0) -> None:
    """Write a slice header (I=7 / P=5 all-slices-same-type variants).

    Assumes the SPS/PPS above: frame_num is 4 bits, POC type 2,
    deblocking control present.  With ``cabac`` (PPS
    entropy_coding_mode_flag=1), P slices carry cabac_init_idc
    (spec 7.3.3) — the caller appends cabac_alignment_one_bit padding
    before the arithmetic-coded slice data.
    """
    write_ue(bw, first_mb)           # first_mb_in_slice
    write_ue(bw, slice_type)         # 7 = I (all), 5 = P (all)
    write_ue(bw, 0)                  # pic_parameter_set_id
    bw.write(frame_num & 0xF, 4)     # frame_num
    if idr:
        write_ue(bw, idr_pic_id)     # idr_pic_id
    if slice_type % 5 == 0:          # P slice
        bw.write(0, 1)               # num_ref_idx_active_override_flag
        bw.write(0, 1)               # ref_pic_list_modification_flag_l0
    if idr:
        bw.write(0, 1)               # no_output_of_prior_pics_flag
        bw.write(0, 1)               # long_term_reference_flag
    elif slice_type % 5 == 0:
        bw.write(0, 1)               # adaptive_ref_pic_marking_mode_flag
    if cabac and slice_type % 5 != 2 and slice_type % 5 != 4:
        write_ue(bw, cabac_init_idc)  # cabac_init_idc (P slices)
    write_se(bw, qp_delta)           # slice_qp_delta
    write_ue(bw, deblocking_idc)     # disable_deblocking_filter_idc
    if deblocking_idc != 1:
        write_se(bw, 0)              # slice_alpha_c0_offset_div2
        write_se(bw, 0)              # slice_beta_offset_div2
