"""Per-frame optimal JPEG Huffman tables + baseline entropy encoding.

Instead of hardcoding the T.81 Annex-K example tables, each frame gets
canonical Huffman codes built from its own symbol histogram (T.81 Annex K.2
procedure: Huffman growth, 16-bit depth adjustment, reserved all-ones code).
The DHT segment then self-describes the exact codes used — better compression
than the example tables and no table-transcription risk.

The symbol alphabets are the standard baseline ones:
- DC: SIZE category 0..11 of the DC difference.
- AC: RRRRSSSS = (zero-run << 4) | size, plus EOB (0x00) and ZRL (0xF0).
"""

from __future__ import annotations

import numpy as np

from .bitwriter import BitWriter


# ---------------------------------------------------------------------------
# Canonical code construction (T.81 Annex K.2)
# ---------------------------------------------------------------------------

def build_code_lengths(freqs: np.ndarray, max_len: int = 16) -> np.ndarray:
    """Return per-symbol code lengths for the given frequencies.

    Implements the JPEG reference procedure: pairwise merge of the two least
    frequent "packages" tracked via CODESIZE/OTHERS, then Adjust_BITS to cap
    lengths at ``max_len``.  A reserved pseudo-symbol with frequency 1 is
    appended so no real symbol is assigned the all-ones code.
    """
    n = len(freqs)
    freq = np.zeros(n + 1, dtype=np.int64)
    freq[:n] = freqs
    freq[n] = 1  # reserved symbol, gets the longest code
    codesize = np.zeros(n + 1, dtype=np.int64)
    others = np.full(n + 1, -1, dtype=np.int64)

    while True:
        present = np.where(freq > 0)[0]
        if len(present) <= 1:
            break
        # v1: least-frequent (highest index breaks ties per spec)
        fmin = freq[present].min()
        v1 = present[freq[present] == fmin].max()
        rest = present[present != v1]
        fmin2 = freq[rest].min()
        v2 = rest[freq[rest] == fmin2].max()

        freq[v1] += freq[v2]
        freq[v2] = 0
        codesize[v1] += 1
        while others[v1] != -1:
            v1 = others[v1]
            codesize[v1] += 1
        others[v1] = v2
        codesize[v2] += 1
        while others[v2] != -1:
            v2 = others[v2]
            codesize[v2] += 1

    # BITS[l] = number of codes of length l
    bits = np.zeros(max(33, codesize.max() + 1), dtype=np.int64)
    for size in codesize:
        if size > 0:
            bits[size] += 1

    # Adjust_BITS: fold lengths > max_len down (spec figure K.3)
    i = len(bits) - 1
    while i > max_len:
        while bits[i] > 0:
            j = i - 2
            while bits[j] == 0:
                j -= 1
            bits[i] -= 2
            bits[i - 1] += 1
            bits[j + 1] += 2
            bits[j] -= 1
        i -= 1
    # Remove the reserved symbol's code (the longest one)
    i = max_len
    while bits[i] == 0:
        i -= 1
    bits[i] -= 1

    # Sort symbols by (codesize, symbol) -> canonical order, assign lengths
    real_sizes = codesize[:n]
    order = np.argsort(real_sizes * 4096 + np.arange(n))  # stable by size then index
    order = order[real_sizes[order] > 0]

    lengths = np.zeros(n, dtype=np.int32)
    li = 1
    counts = bits.copy()
    for sym in order:
        while counts[li] == 0:
            li += 1
        lengths[sym] = li
        counts[li] -= 1
    return lengths


def canonical_codes(lengths: np.ndarray):
    """(code, length) per symbol from canonical lengths (shorter first,
    then smaller symbol value).  Returns (codes, lengths, bits, huffval):
    ``bits``/``huffval`` are the DHT wire form.
    """
    n = len(lengths)
    syms = [s for s in range(n) if lengths[s] > 0]
    syms.sort(key=lambda s: (lengths[s], s))
    codes = np.zeros(n, dtype=np.int64)
    code = 0
    prev_len = 0
    bits = np.zeros(17, dtype=np.int64)
    huffval = []
    for s in syms:
        code <<= (lengths[s] - prev_len)
        codes[s] = code
        code += 1
        prev_len = lengths[s]
        bits[lengths[s]] += 1
        huffval.append(s)
    return codes, lengths, bits[1:17], np.array(huffval, dtype=np.uint8)


# ---------------------------------------------------------------------------
# Symbol extraction (vectorized where possible)
# ---------------------------------------------------------------------------

def block_symbols(zz: np.ndarray, prev_dc: int):
    """Extract the Huffman symbols of one zigzagged block.

    zz: int array of 64 coefficients in zigzag order.
    Returns (dc_entry, ac_entries, new_prev_dc) where entries carry
    (symbol, amplitude_bits_value, nbits).  This is the single source of
    truth for symbol extraction — histogramming and emission both consume
    its output, so tables and scan can never disagree.
    """
    diff = int(zz[0]) - prev_dc
    dc_size = abs(diff).bit_length()
    amp = diff if diff >= 0 else diff + (1 << dc_size) - 1
    dc_entry = (dc_size, amp, dc_size)

    ac_entries = []
    nz_idx = np.nonzero(zz[1:])[0]
    prev = -1
    for idx in nz_idx:
        run = int(idx) - prev - 1
        while run >= 16:
            ac_entries.append((0xF0, 0, 0))  # ZRL
            run -= 16
        v = int(zz[idx + 1])
        size = abs(v).bit_length()
        a = v if v >= 0 else v + (1 << size) - 1
        ac_entries.append(((run << 4) | size, a, size))
        prev = int(idx)
    if prev < 62:
        ac_entries.append((0x00, 0, 0))  # EOB
    return dc_entry, ac_entries, int(zz[0])


def frame_symbols(blocks_per_comp, comp_table_ids):
    """Run :func:`block_symbols` over every block of every component.

    blocks_per_comp: list of (nblk, 64) int arrays in per-component scan
    order.  Returns (symbols_per_comp, dc_hist, ac_hist): the symbol lists
    to emit, and their histograms per table id (0 luma / 1 chroma).
    """
    dc_hist = [np.zeros(17, dtype=np.int64) for _ in range(2)]
    ac_hist = [np.zeros(256, dtype=np.int64) for _ in range(2)]
    symbols_per_comp = []
    for comp, tid in zip(blocks_per_comp, comp_table_ids):
        zz = np.asarray(comp)
        prev_dc = 0
        entries = []
        for b in range(zz.shape[0]):
            dc_entry, ac_entries, prev_dc = block_symbols(zz[b], prev_dc)
            entries.append((dc_entry, ac_entries))
            dc_hist[tid][dc_entry[0]] += 1
            for sym, _, _ in ac_entries:
                ac_hist[tid][sym] += 1
        symbols_per_comp.append(entries)
    return symbols_per_comp, dc_hist, ac_hist


class HuffmanTable:
    """Encode-side Huffman table with DHT wire form."""

    def __init__(self, freqs: np.ndarray):
        freqs = np.asarray(freqs, dtype=np.int64).copy()
        if freqs.sum() == 0:
            freqs[0] = 1  # degenerate: ensure at least one code exists
        lengths = build_code_lengths(freqs)
        self.codes, self.lengths, self.bits, self.huffval = canonical_codes(lengths)

    def emit(self, bw: BitWriter, symbol: int) -> None:
        bw.write(int(self.codes[symbol]), int(self.lengths[symbol]))

    def dht_payload(self, table_class: int, table_id: int) -> bytes:
        out = bytearray([(table_class << 4) | table_id])
        out += bytes(int(b) for b in self.bits)
        out += bytes(self.huffval.tolist())
        return bytes(out)
