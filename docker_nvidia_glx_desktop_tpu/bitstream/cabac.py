"""CABAC entropy coding (spec 9.3) for the rebuild's H.264 syntax subset.

Replaces CAVLC bit emission with context-adaptive binary arithmetic
coding — the reference's default encoder ``nvh264enc`` emits Main-profile
CABAC streams (reference Dockerfile:210), worth ~10-15% bitrate at equal
quality.  Normative tables come from :mod:`.cabac_tables` (recovered from
system libx264/libavcodec and cross-validated).

The slice-per-MB-row structure the whole codec is built around carries
over unchanged: every row is its own slice with its own arithmetic-engine
init, so rows stay independently codable (host thread-parallel in the
C++ twin, device-parallel later) and the CAVLC paths' availability rules
(top neighbors never available) apply to context derivation too.

Syntax subset coded here (matching the CAVLC layer, h264_entropy.py):
- I slices: I_16x16 (4 pred modes) and I_NxN macroblocks, chroma DC mode
- P slices: P_L0_16x16 + P_Skip, single reference, no sub-partitions
"""

from __future__ import annotations

import numpy as np

from .cabac_tables import engine_tables, init_contexts

# zigzag scan for 4x4 blocks (coefficient lists arrive already in zigzag
# order from the device stage, same contract as the CAVLC layer)

# ctxBlockCat offsets (spec 9.3.3.1.3, Table 9-40)
_CBF_OFF = {0: 0, 1: 4, 2: 8, 3: 12, 4: 16}       # coded_block_flag, base 85
_SIG_OFF = {0: 0, 1: 15, 2: 29, 3: 44, 4: 47}     # significant_coeff, 105
_LAST_OFF = {0: 0, 1: 15, 2: 29, 3: 44, 4: 47}    # last_significant, 166
_ABS_OFF = {0: 0, 1: 10, 2: 20, 3: 30, 4: 39}     # coeff_abs_level_m1, 227

# luma4x4BlkIdx -> (bx, by), the z-scan (matches h264_entropy._BLK_XY)
_BLK_XY = [(0, 0), (1, 0), (0, 1), (1, 1),
           (2, 0), (3, 0), (2, 1), (3, 1),
           (0, 2), (1, 2), (0, 3), (1, 3),
           (2, 2), (3, 2), (2, 3), (3, 3)]


class CabacEncoder:
    """The arithmetic coding engine (spec 9.3.4) for ONE slice.

    ``table_idx``: 0 for I slices, 1 + cabac_init_idc for P slices.
    Output via :meth:`get_bytes` after :meth:`finish` — starts at a byte
    boundary (the caller byte-aligns the slice header first with
    cabac_alignment_one_bit padding)."""

    def __init__(self, table_idx: int, qp: int):
        rng, tmps, tlps = engine_tables()
        self._rng_lps = rng
        self._tmps = tmps
        self._tlps = tlps
        st, mps = init_contexts(table_idx, qp)
        self.state = st.astype(np.int32)
        self.mps = mps.astype(np.int32)
        self.low = 0
        self.range = 510
        self._outstanding = 0
        self._first = True
        self._bits = []                  # appended MSB-first

    # -- bit plumbing (9.3.4.2: PutBit / WriteBits) --------------------

    def _put(self, b: int) -> None:
        if self._first:
            self._first = False
        else:
            self._bits.append(b)
        while self._outstanding > 0:
            self._bits.append(1 - b)
            self._outstanding -= 1

    def _renorm(self) -> None:
        while self.range < 256:
            if self.low < 256:
                self._put(0)
            elif self.low >= 512:
                self.low -= 512
                self._put(1)
            else:
                self.low -= 256
                self._outstanding += 1
            self.range <<= 1
            self.low <<= 1

    # -- coding primitives (9.3.4.3) -----------------------------------

    def decision(self, ctx: int, b: int) -> None:
        s = int(self.state[ctx])
        r_lps = int(self._rng_lps[s][(self.range >> 6) & 3])
        self.range -= r_lps
        if b != self.mps[ctx]:
            self.low += self.range
            self.range = r_lps
            if s == 0:
                self.mps[ctx] ^= 1
            self.state[ctx] = self._tlps[s]
        else:
            self.state[ctx] = self._tmps[s]
        self._renorm()

    def bypass(self, b: int) -> None:
        self.low <<= 1
        if b:
            self.low += self.range
        if self.low >= 1024:
            self.low -= 1024
            self._put(1)
        elif self.low < 512:
            self._put(0)
        else:
            self.low -= 512
            self._outstanding += 1

    def terminate(self, b: int) -> None:
        self.range -= 2
        if b:
            self.low += self.range
            self.range = 2
            self._renorm()
            self._put((self.low >> 9) & 1)
            # WriteBits(((low >> 7) & 3) | 1, 2): the final 1 is the
            # rbsp_stop_one_bit
            v = ((self.low >> 7) & 3) | 1
            self._bits.append((v >> 1) & 1)
            self._bits.append(v & 1)
        else:
            self._renorm()

    def get_bytes(self) -> bytes:
        """Byte-aligned slice data (call after terminate(1)); pads the
        tail with rbsp_alignment_zero_bits."""
        bits = self._bits
        out = bytearray()
        acc = 0
        for i, b in enumerate(bits):
            acc = (acc << 1) | b
            if (i & 7) == 7:
                out.append(acc)
                acc = 0
        if len(bits) & 7:
            out.append(acc << (8 - (len(bits) & 7)))
        return bytes(out)

    # -- shared binarization helpers -----------------------------------

    def tu(self, v: int, cmax: int, ctxs) -> None:
        """Truncated unary: v ones then a zero (omitted at cmax);
        ``ctxs[i]`` is the context for bin i (last entry reused)."""
        for i in range(v):
            self.decision(ctxs[min(i, len(ctxs) - 1)], 1)
        if v < cmax:
            self.decision(ctxs[min(v, len(ctxs) - 1)], 0)

    def ueg_suffix(self, v: int, k: int) -> None:
        """Exp-Golomb order-k suffix in bypass (9.3.2.3)."""
        while v >= (1 << k):
            self.bypass(1)
            v -= 1 << k
            k += 1
        self.bypass(0)
        for i in reversed(range(k)):
            self.bypass((v >> i) & 1)


class _MbCtx:
    """Per-MB left-neighbor context snapshot (top is never available
    under slice-per-row)."""

    __slots__ = ("intra", "i16", "skip", "cbf_luma", "cbf_luma_dc",
                 "cbf_cb", "cbf_cr", "cbf_cb_dc", "cbf_cr_dc",
                 "cbp_luma", "cbp_chroma", "abs_mvd", "modes")

    def __init__(self):
        self.intra = False
        self.i16 = False
        self.skip = False
        self.cbf_luma = np.zeros((4, 4), np.int32)     # [by][bx]
        self.cbf_luma_dc = 0
        self.cbf_cb = np.zeros((2, 2), np.int32)
        self.cbf_cr = np.zeros((2, 2), np.int32)
        self.cbf_cb_dc = 0
        self.cbf_cr_dc = 0
        self.cbp_luma = 0
        self.cbp_chroma = 0
        self.abs_mvd = np.zeros(2, np.int32)
        self.modes = np.full((4, 4), 2, np.int32)      # I4x4 pred modes


class SliceCoder:
    """Entropy-codes one MB-row slice.  ``enc`` is a fresh CabacEncoder;
    the caller writes the (byte-aligned) slice header separately."""

    def __init__(self, enc: CabacEncoder, intra_slice: bool):
        self.e = enc
        self.intra_slice = intra_slice
        self.left: _MbCtx | None = None   # None = MB column 0
        self._prev_qp_delta_nz = 0

    # -- residual block (9.3.3.1.3) ------------------------------------

    def residual(self, coeffs, cat: int, cbf_ctx_inc: int) -> int:
        """coded_block_flag + significance map + levels for one block.
        Returns the coded cbf (0/1)."""
        e = self.e
        coeffs = [int(c) for c in coeffs]
        nz = [i for i, c in enumerate(coeffs) if c]
        cbf = 1 if nz else 0
        e.decision(85 + _CBF_OFF[cat] + cbf_ctx_inc, cbf)
        if not cbf:
            return 0
        n = len(coeffs)
        last_nz = nz[-1]
        sig_base = 105 + _SIG_OFF[cat]
        last_base = 166 + _LAST_OFF[cat]
        for i in range(n - 1):
            inc = min(i, 2) if cat == 3 else i
            sig = 1 if coeffs[i] else 0
            e.decision(sig_base + inc, sig)
            if sig:
                e.decision(last_base + inc, 1 if i == last_nz else 0)
                if i == last_nz:
                    break
        # levels, reverse scan order over significant positions
        abs_base = 227 + _ABS_OFF[cat]
        num_eq1 = 0
        num_gt1 = 0
        for i in reversed(nz):
            lvl = abs(coeffs[i]) - 1          # coeff_abs_level_minus1
            c0 = abs_base + (0 if num_gt1 else min(4, 1 + num_eq1))
            cn = abs_base + 5 + min(3 if cat == 3 else 4, num_gt1)
            prefix = min(lvl, 14)
            for k in range(prefix):
                e.decision(c0 if k == 0 else cn, 1)
            if prefix < 14:
                e.decision(c0 if prefix == 0 else cn, 0)
            else:
                e.ueg_suffix(lvl - 14, 0)
            e.bypass(1 if coeffs[i] < 0 else 0)
            if lvl == 0:
                num_eq1 += 1
            else:
                num_gt1 += 1
        return 1

    # -- macroblock-level elements -------------------------------------

    def mb_skip(self, skip: bool) -> None:
        left = self.left
        inc = 1 if (left is not None and not left.skip) else 0
        self.e.decision(11 + inc, 1 if skip else 0)

    def mb_type_i(self, i4: bool, pred_mode: int, cbp_luma_nz: bool,
                  cbp_chroma: int) -> None:
        """mb_type for I slices (and the intra suffix in P slices)."""
        e = self.e
        if self.intra_slice:
            left = self.left
            # condTermN = 0 iff mbN unavailable or mbN is I_NxN; the top
            # MB is another slice, so condTermB is always 0
            inc = (1 if (left is not None and left.i16) else 0)
            e.decision(3 + inc, 0 if i4 else 1)
            if i4:
                return
            base = 3 + 2               # I-slice suffix contexts 6..10
            e.terminate(0)             # not I_PCM
            e.decision(base + 1, 1 if cbp_luma_nz else 0)
            e.decision(base + 2, 1 if cbp_chroma else 0)
            if cbp_chroma:
                e.decision(base + 3, 1 if cbp_chroma == 2 else 0)
            e.decision(base + 4, (pred_mode >> 1) & 1)
            e.decision(base + 5, pred_mode & 1)
        else:
            # intra in P: prefix bin 1 then suffix at base 17 with
            # SHARED chroma/pred contexts (lavc decode_cabac_mb_type)
            e.decision(14, 1)
            e.decision(17, 0 if i4 else 1)
            if i4:
                return
            e.terminate(0)
            e.decision(18, 1 if cbp_luma_nz else 0)
            e.decision(19, 1 if cbp_chroma else 0)
            if cbp_chroma:
                e.decision(19, 1 if cbp_chroma == 2 else 0)
            e.decision(20, (pred_mode >> 1) & 1)
            e.decision(20, pred_mode & 1)

    def mb_type_p16(self) -> None:
        """P_L0_16x16: prefix bin string "000" (ctx 14, 15, 16 —
        validated against the libavcodec decoder; "001" is P_8x8)."""
        e = self.e
        e.decision(14, 0)
        e.decision(15, 0)
        e.decision(16, 0)

    def mvd(self, comp: int, val: int) -> None:
        """mvd_l0 component (0 = x, 1 = y), UEG3 uCoff=9 + sign."""
        e = self.e
        base = 40 if comp == 0 else 47
        left = self.left
        s = int(left.abs_mvd[comp]) if left is not None else 0
        inc = 0 if s < 3 else (1 if s <= 32 else 2)
        a = abs(val)
        prefix = min(a, 9)
        ctxs = [base + inc, base + 3, base + 4, base + 5, base + 6]
        for k in range(prefix):
            e.decision(ctxs[min(k, 4)], 1)
        if prefix < 9:
            e.decision(ctxs[min(prefix, 4)], 0)
        else:
            e.ueg_suffix(a - 9, 3)
        if a:
            e.bypass(1 if val < 0 else 0)

    def intra_chroma_mode(self, mode: int) -> None:
        """condTermN = (mbN available, intra, chroma mode != 0).  This
        encoder always codes chroma DC (mode 0), so the left term is
        identically 0 — kept explicit so a future chroma-mode decision
        only needs to track the left mode in _MbCtx."""
        inc = 0
        e = self.e
        if mode == 0:
            e.decision(64 + inc, 0)
        else:
            e.decision(64 + inc, 1)
            e.tu(mode - 1, 2, [67])

    def i4_pred_mode(self, mode: int, pred: int) -> None:
        e = self.e
        if mode == pred:
            e.decision(68, 1)
        else:
            e.decision(68, 0)
            rem = mode - 1 if mode > pred else mode
            e.decision(69, rem & 1)
            e.decision(69, (rem >> 1) & 1)
            e.decision(69, (rem >> 2) & 1)

    def cbp(self, cbp_luma: int, cbp_chroma: int) -> None:
        """coded_block_pattern for I_NxN / P MBs (4 luma bins + chroma)."""
        e = self.e
        left = self.left
        # luma: 8x8 indices 0..3 (z-order: 0 tl, 1 tr, 2 bl, 3 br)
        for b in range(4):
            if b & 1:                       # right half: left nb in-MB
                a_bit = (cbp_luma >> (b - 1)) & 1
                a_avail = True
            else:                           # left half: from left MB
                a_bit = ((left.cbp_luma >> (b + 1)) & 1
                         if left is not None else 0)
                a_avail = left is not None
            if b & 2:                       # bottom: top nb in-MB
                b_bit = (cbp_luma >> (b - 2)) & 1
                b_avail = True
            else:
                b_bit = 0
                b_avail = False             # top MB: other slice
            inc = ((1 if (a_avail and not a_bit) else (0 if a_avail else 0))
                   + 2 * (1 if (b_avail and not b_bit) else 0))
            e.decision(73 + inc, (cbp_luma >> b) & 1)
        ca = left.cbp_chroma if left is not None else 0
        inc = (1 if ca > 0 else 0)          # top: unavailable -> 0
        e.decision(77 + inc, 1 if cbp_chroma else 0)
        if cbp_chroma:
            inc = (1 if ca == 2 else 0)
            e.decision(81 + inc, 1 if cbp_chroma == 2 else 0)

    def qp_delta(self, v: int) -> None:
        e = self.e
        mapped = 2 * abs(v) - (1 if v > 0 else 0)
        ctxs = [60 + self._prev_qp_delta_nz, 62, 63]
        for i in range(mapped):
            e.decision(ctxs[min(i, 2)], 1)
        e.decision(ctxs[min(mapped, 2)], 0)
        self._prev_qp_delta_nz = 1 if v else 0

    def qp_delta_absent(self) -> None:
        """An MB with no mb_qp_delta syntax (cbp==0 non-I16, or skip)
        infers mb_qp_delta = 0 — and the ctx for the NEXT coded one keys
        off the previous MB in decoding order (spec 9.3.3.1.1.5), so the
        flag must clear here or encoder and decoder pick different
        contexts."""
        self._prev_qp_delta_nz = 0

    def end_of_slice(self, last: bool) -> None:
        self.e.terminate(1 if last else 0)

    # -- coded_block_flag neighbor helpers ------------------------------

    def cbf_inc_luma(self, cur_cbf, bx: int, by: int, intra: bool) -> int:
        """ctxIdxInc for a luma 4x4 block at raster (bx, by) given the
        current MB's in-progress cbf grid ``cur_cbf`` [by][bx]."""
        left = self.left
        if bx > 0:
            a = int(cur_cbf[by][bx - 1])
        elif left is not None and not left.skip:
            a = int(left.cbf_luma[by][3])
        elif left is not None:
            a = 0
        else:
            a = 1 if intra else 0        # unavailable
        if by > 0:
            b = int(cur_cbf[by - 1][bx])
        else:
            b = 1 if intra else 0        # top MB: other slice
        return a + 2 * b

    def cbf_inc_chroma(self, cur, grid_attr: str, bx: int, by: int,
                       intra: bool) -> int:
        left = self.left
        if bx > 0:
            a = int(cur[by][bx - 1])
        elif left is not None and not left.skip:
            a = int(getattr(left, grid_attr)[by][1])
        elif left is not None:
            a = 0
        else:
            a = 1 if intra else 0
        if by > 0:
            b = int(cur[by - 1][bx])
        else:
            b = 1 if intra else 0
        return a + 2 * b

    def cbf_inc_dc(self, attr: str, intra: bool, require_i16: bool = False
                   ) -> int:
        left = self.left
        if left is None:
            a = 1 if intra else 0
        elif left.skip or (require_i16 and not left.i16):
            a = 0
        else:
            a = int(getattr(left, attr))
        b = 1 if intra else 0            # top MB: other slice
        return a + 2 * b
