"""VP8 interframe bitstream serialization (RFC 6386 §8-9, §16-18).

The reference's ``vp8enc`` element (reference Dockerfile:210,453-455)
codes full inter frames; round 4 shipped keyframe-only VP8 — every
frame a sync point, a bitrate disaster at 1080p (VERDICT r4 item 3).
This module adds the missing layer: the interframe feature header, the
per-MB mode/reference/MV partition (including the §8.3 near-MV survey
that both the mv_ref tree probabilities and NEARMV semantics depend
on), and the §17 motion-vector component coder.  Probability tables
come from the system libvpx (``vp8_tables``: mv_default / mv_update /
mode_contexts) and the whole construction is validated the same way as
the keyframe path: the libvpx *decoder* must reproduce the encoder's
reconstruction byte-exactly.

Encoder policy: every MB is inter against the LAST frame
(refresh_last=1, golden/altref never touched), mv_mode in {ZEROMV,
NEWMV, NEARESTMV, NEARMV}, full-pel motion (desktop motion — window
drags, scrolls — is integer-pixel; odd components cost only the
chroma phase-4 six-tap in models/vp8._mc_chroma).
"""

from __future__ import annotations

import struct
from typing import List, Tuple

import numpy as np

from .vp8_bool import BoolEncoder
from .vp8_tables import Vp8Tables

__all__ = ["write_interframe_header", "find_near_mvs", "mv_ref_probs",
           "write_mb_inter", "serialize_interframe",
           "ZEROMV", "NEARESTMV", "NEARMV", "NEWMV"]

# mv_ref tree modes (tree: {-ZERO, 2, -NEAREST, 4, -NEAR, 6, -NEW, -SPLIT})
ZEROMV, NEARESTMV, NEARMV, NEWMV, SPLITMV = 0, 1, 2, 3, 4

_MV_REF_BITS = {
    ZEROMV: ((0, 0),),
    NEARESTMV: ((1, 0), (0, 1)),
    NEARMV: ((1, 0), (1, 1), (0, 2)),
    NEWMV: ((1, 0), (1, 1), (1, 2), (0, 3)),
    SPLITMV: ((1, 0), (1, 1), (1, 2), (1, 3)),
}

# Chosen header literals: all MBs are inter vs LAST, so make the
# is_inter bit ~free (prob of the zero/intra branch minimal) and the
# LAST-reference bit ~free (prob of zero/LAST branch maximal).
PROB_INTRA = 1
PROB_LAST = 255
PROB_GF = 128


def write_interframe_header(bc: BoolEncoder, tables: Vp8Tables,
                            q_index: int,
                            refresh_golden: bool = False) -> None:
    """Interframe feature header (§9.2-9.11): no segmentation, loop
    filter off, one token partition, flat quantizers, refresh LAST
    (plus GOLDEN on a tune=hq refresh frame — §9.7: the
    copy_buffer_to_golden field exists only when refresh_golden is 0),
    no entropy refresh, no prob updates."""
    bc.encode(0, 128)                 # segmentation_enabled
    bc.encode(0, 128)                 # filter_type
    bc.literal(0, 6)                  # loop_filter_level = 0
    bc.literal(0, 3)                  # sharpness
    bc.encode(0, 128)                 # loop_filter_adj_enabled
    bc.literal(0, 2)                  # log2(token partitions) = 0
    bc.literal(q_index, 7)            # y_ac_qi
    for _ in range(5):                # quantizer deltas absent
        bc.encode(0, 128)
    bc.encode(1 if refresh_golden else 0, 128)   # refresh_golden_frame
    bc.encode(0, 128)                 # refresh_alternate_frame
    if not refresh_golden:
        bc.literal(0, 2)              # copy_buffer_to_golden = none
    bc.literal(0, 2)                  # copy_buffer_to_alternate = none
    bc.encode(0, 128)                 # sign_bias_golden
    bc.encode(0, 128)                 # sign_bias_alternate
    bc.encode(0, 128)                 # refresh_entropy_probs
    bc.encode(1, 128)                 # refresh_last_frame
    upd = tables.coef_update_probs
    for i in range(4):
        for j in range(8):
            for k in range(3):
                for l in range(11):
                    bc.encode(0, int(upd[i, j, k, l]))
    bc.encode(0, 128)                 # mb_no_coeff_skip
    bc.literal(PROB_INTRA, 8)
    bc.literal(PROB_LAST, 8)
    bc.literal(PROB_GF, 8)
    bc.encode(0, 128)                 # intra_16x16_prob_update_flag
    bc.encode(0, 128)                 # intra_chroma_prob_update_flag
    mvu = tables.mv_update
    for comp in range(2):
        for i in range(19):
            bc.encode(0, int(mvu[comp, i]))


# ---------------------------------------------------------------------------
# §8.3 near-MV survey.  MV units here are the bitstream's internal
# eighth-pel (row, col) pairs; our full-pel policy means multiples of 8.
# ---------------------------------------------------------------------------

def find_near_mvs(is_inter: np.ndarray, mvs: np.ndarray, r: int, c: int
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                             List[int]]:
    """Survey above/left/above-left neighbors (weights 2/2/1).

    ``is_inter``: (R, C) bool of already-coded MBs; ``mvs``: (R, C, 2)
    int32 eighth-pel (row, col).  Returns (nearest, near, best_mv,
    cnt[4]).  Out-of-frame neighbors count as intra (the decoder's
    zero-initialized border).  Sign bias is identically zero here (only
    LAST is referenced), so no mv flipping.
    """
    near: List[np.ndarray] = [np.zeros(2, np.int32)]
    cnt = [0, 0, 0, 0]

    def probe(rr: int, cc: int, weight: int) -> None:
        if rr < 0 or cc < 0 or not is_inter[rr, cc]:
            return
        mv = mvs[rr, cc]
        if mv.any():
            if len(near) > 1 and (near[-1] == mv).all():
                cnt[len(near) - 1] += weight
            else:
                near.append(mv.copy())
                cnt[len(near) - 1] += weight
        else:
            cnt[0] += weight

    probe(r - 1, c, 2)
    probe(r, c - 1, 2)
    probe(r - 1, c - 1, 1)
    # Three distinct nonzero MVs: the distinctness probe compares only
    # against the LAST slot, so the third may still equal the first —
    # the decoder then boosts the nearest count by 1 (findnearmv's
    # "see if above-left MV matches this MV" fixup); missing this
    # diverges the mv_ref probabilities and desyncs the bool decoder.
    if len(near) == 4 and (near[3] == near[1]).all():
        cnt[1] += 1
    # cnt[3] is then OVERWRITTEN with the SPLITMV neighbor count — we
    # never code SPLITMV, so it is always 0 (the third distinct MV's
    # transient weight must not leak into the NEWMV probability).
    cnt[3] = 0
    while len(near) < 3:
        near.append(np.zeros(2, np.int32))
    if cnt[2] > cnt[1]:
        near[1], near[2] = near[2], near[1]
        cnt[1], cnt[2] = cnt[2], cnt[1]
    best = near[1] if cnt[1] >= cnt[0] else near[0]
    return near[1], near[2], best.copy(), cnt


def mv_ref_probs(tables: Vp8Tables, cnt: List[int]) -> List[int]:
    mc = tables.mode_contexts
    return [int(mc[min(cnt[i], 5), i]) for i in range(4)]


# ---------------------------------------------------------------------------
# §17 MV component coder
# ---------------------------------------------------------------------------

# small_mvtree: {2, 8, 4, 6, -0, -1, -2, -3, 10, 12, -4, -5, -6, -7};
# probs p[2 + node/2] -> precomputed (bit, prob-index) paths for 0..7
_SMALL_TREE = (2, 8, 4, 6, -0, -1, -2, -3, 10, 12, -4, -5, -6, -7)
_SMALL_PATHS: List[List[Tuple[int, int]]] = [[] for _ in range(8)]


def _walk_small(i: int, path) -> None:
    for b in (0, 1):
        nxt = _SMALL_TREE[i + b]
        if nxt <= 0:
            _SMALL_PATHS[-nxt] = path + [(b, i >> 1)]
        else:
            _walk_small(nxt, path + [(b, i >> 1)])


_walk_small(0, [])


def encode_mv_component(bc: BoolEncoder, v8: int, probs: np.ndarray
                        ) -> None:
    """One MV component delta in eighth-pel units; coded at quarter-pel
    (§17.2: the decoder doubles the read value)."""
    assert v8 % 2 == 0, "VP8 codes MVs at quarter-pel precision"
    v = v8 // 2
    x = abs(v)
    assert x < 1024
    if x < 8:
        bc.encode(0, int(probs[0]))                  # is_short = short
        for b, node in _SMALL_PATHS[x]:
            bc.encode(b, int(probs[2 + node]))
        if x:
            bc.encode(1 if v < 0 else 0, int(probs[1]))
    else:
        bc.encode(1, int(probs[0]))
        for i in range(3):
            bc.encode((x >> i) & 1, int(probs[9 + i]))
        for i in range(9, 3, -1):
            bc.encode((x >> i) & 1, int(probs[9 + i]))
        if x & 0xFFF0:                               # bit 3 implied 1
            bc.encode((x >> 3) & 1, int(probs[9 + 3]))
        bc.encode(1 if v < 0 else 0, int(probs[1]))


def write_mb_inter(bc: BoolEncoder, tables: Vp8Tables, mode: int,
                   mv8, best_mv, cnt: List[int],
                   ref_golden: bool = False) -> None:
    """One MB's inter mode (+ MV for NEWMV) into the first partition.

    ``ref_golden`` (tune=hq): predict from the GOLDEN buffer instead of
    LAST — the non-LAST branch of the reference tree (prob_last) then
    the golden side of prob_gf (§9.10/16.1).  Sign biases are both 0 so
    the §8.3 near-MV survey needs no mv flipping either way."""
    bc.encode(1, PROB_INTRA)                         # inter MB
    if ref_golden:
        bc.encode(1, PROB_LAST)                      # not LAST
        bc.encode(0, PROB_GF)                        # GOLDEN (not altref)
    else:
        bc.encode(0, PROB_LAST)                      # LAST reference
    probs = mv_ref_probs(tables, cnt)
    for b, node in _MV_REF_BITS[mode]:
        bc.encode(b, probs[node])
    if mode == NEWMV:
        d_row = int(mv8[0]) - int(best_mv[0])
        d_col = int(mv8[1]) - int(best_mv[1])
        encode_mv_component(bc, d_row, tables.mv_default[0])
        encode_mv_component(bc, d_col, tables.mv_default[1])


def serialize_interframe(part1: bytes, part2: bytes) -> bytes:
    """Frame tag + partitions (§9.1; no start code / dims on inter)."""
    tag = (1 << 0) | (0 << 1) | (1 << 4) | (len(part1) << 5)
    return struct.pack("<I", tag)[:3] + part1 + part2
