"""VP8 keyframe bitstream serialization (RFC 6386 §9, §13, §19).

Writes the uncompressed frame tag, the bool-coded first partition
(feature header + per-MB intra modes) and the token partition.  The
probability tables come from ``vp8_tables`` (recovered from libvpx) and
the whole stream is validated by libvpx decode in the golden tests.

Reference parity: this is the role x264's/libvpx's bitstream writers
play behind the reference's ``vp8enc`` element (Dockerfile:210).
"""

from __future__ import annotations

import struct
from typing import List

import numpy as np

from .vp8_bool import BoolEncoder
from .vp8_tables import CAT_BASE, CAT_BITS, COEF_BANDS, ZIGZAG, Vp8Tables

__all__ = ["serialize_keyframe", "TokenState", "ivf_header",
           "ivf_frame_header"]

# token tree (see vp8_tables docstring); leaves negative, probs[i >> 1]
_TREE = [-11, 2,       # EOB(11 used as sentinel leaf id)
         -0, 4,        # ZERO
         -1, 6,        # ONE
         8, 12,
         -2, 10,       # TWO
         -3, -4,       # THREE / FOUR
         14, 16,
         -5, -6,       # CAT1 / CAT2
         18, 20,
         -7, -8,       # CAT3 / CAT4
         -9, -10]      # CAT5 / CAT6

# precomputed (bits, prob-node-indices) per token id 0..11 from start 0
_TOKEN_BITS: List[List[int]] = [[] for _ in range(12)]
_TOKEN_NODES: List[List[int]] = [[] for _ in range(12)]


def _walk(i: int, bits: List[int], nodes: List[int]) -> None:
    for b in (0, 1):
        nxt = _TREE[i + b]
        if nxt <= 0:
            tok = -nxt
            _TOKEN_BITS[tok] = bits + [b]
            _TOKEN_NODES[tok] = nodes + [i >> 1]
        else:
            _walk(nxt, bits + [b], nodes + [i >> 1])


_walk(0, [], [])
EOB_TOKEN = 11


def _token_for(v: int) -> int:
    a = abs(v)
    if a <= 4:
        return a
    for cat in range(6):
        hi = CAT_BASE[cat] + (1 << CAT_BITS[cat]) - 1
        if a <= hi:
            return 5 + cat
    return 10                     # clamp into cat6 (caller clamps coeff)


class TokenState:
    """Above/left nonzero contexts for the token partition."""

    def __init__(self, mb_cols: int):
        self.above_y = np.zeros(mb_cols * 4, np.int32)
        self.above_u = np.zeros(mb_cols * 2, np.int32)
        self.above_v = np.zeros(mb_cols * 2, np.int32)
        self.above_y2 = np.zeros(mb_cols, np.int32)
        self.reset_left()

    def reset_left(self) -> None:
        self.left_y = np.zeros(4, np.int32)
        self.left_u = np.zeros(2, np.int32)
        self.left_v = np.zeros(2, np.int32)
        self.left_y2 = 0


def encode_block_tokens(bc: BoolEncoder, tables: Vp8Tables,
                        block: np.ndarray, block_type: int,
                        first_coeff: int, ctx: int) -> int:
    """Token-code one quantized 4x4 block; returns its nonzero flag."""
    probs = tables.coef_probs[block_type]
    vals = block.reshape(16)[ZIGZAG]
    eob = 0
    for p in range(15, first_coeff - 1, -1):
        if vals[p] != 0:
            eob = p + 1
            break
    prev_zero = False
    for p in range(first_coeff, eob):
        v = int(vals[p])
        band = COEF_BANDS[p]
        tok = _token_for(v)
        bits = _TOKEN_BITS[tok]
        nodes = _TOKEN_NODES[tok]
        skip = 1 if prev_zero else 0     # EOB branch skipped after ZERO
        prob_row = probs[band][ctx]
        for b, n in zip(bits[skip:], nodes[skip:]):
            bc.encode(b, int(prob_row[n]))
        if tok >= 5:                      # category extra bits
            cat = tok - 5
            extra = abs(v) - CAT_BASE[cat]
            pcat = tables.pcat[cat]
            for i in range(CAT_BITS[cat] - 1, -1, -1):
                bc.encode((extra >> i) & 1, pcat[CAT_BITS[cat] - 1 - i])
        if tok != 0:
            bc.encode(1 if v < 0 else 0, 128)   # sign
        # next position's context
        ctx = 0 if v == 0 else (1 if abs(v) == 1 else 2)
        prev_zero = v == 0
    if eob < 16:
        band = COEF_BANDS[eob] if eob > first_coeff else \
            COEF_BANDS[first_coeff]
        prob_row = probs[band][ctx]
        # EOB is only codable when the previous token wasn't ZERO (it
        # never is here: trailing zeros are not emitted)
        bc.encode(_TOKEN_BITS[EOB_TOKEN][0], int(prob_row[0]))
    return 1 if eob > first_coeff else 0


def write_keyframe_header(bc: BoolEncoder, tables: Vp8Tables,
                          q_index: int) -> None:
    """Feature header for our keyframes: no segmentation, loop filter
    off (the recon contract with the parallel design — same choice as
    the H.264 path's disable_deblocking), one token partition, flat
    quantizers, no prob updates, no skip flags."""
    bc.encode(0, 128)                 # color_space
    bc.encode(0, 128)                 # clamping_type
    bc.encode(0, 128)                 # segmentation_enabled
    bc.encode(0, 128)                 # filter_type
    bc.literal(0, 6)                  # loop_filter_level = 0 (off)
    bc.literal(0, 3)                  # sharpness
    bc.encode(0, 128)                 # loop_filter_adj_enabled
    bc.literal(0, 2)                  # log2(token partitions) = 0 -> 1
    bc.literal(q_index, 7)            # y_ac_qi
    for _ in range(5):                # all quantizer deltas absent
        bc.encode(0, 128)
    bc.encode(0, 128)                 # refresh_entropy_probs
    upd = tables.coef_update_probs
    for i in range(4):
        for j in range(8):
            for k in range(3):
                for l in range(11):
                    bc.encode(0, int(upd[i, j, k, l]))
    bc.encode(0, 128)                 # mb_no_coeff_skip = 0 (no skip)


def write_mb_modes_v_pred(bc: BoolEncoder, tables: Vp8Tables,
                          mb_count: int) -> None:
    """All MBs use V_PRED luma + V_PRED chroma (above-row prediction —
    the choice that removes every left-neighbor dependency, which is
    what makes the row-parallel TPU pipeline possible; kf trees §11.2)."""
    ky = tables.kf_ymode_prob
    kuv = tables.kf_uv_mode_prob
    for _ in range(mb_count):
        # kf ymode tree {-B,2,4,6,-DC,-V,-H,-TM}: V = 1,0,1
        bc.encode(1, int(ky[0]))
        bc.encode(0, int(ky[1]))
        bc.encode(1, int(ky[2]))
        # uv tree {-DC,2,-V,4,-H,-TM}: V = 1,0
        bc.encode(1, int(kuv[0]))
        bc.encode(0, int(kuv[1]))


def serialize_keyframe(width: int, height: int, part1: bytes,
                       part2: bytes) -> bytes:
    """Frame tag + start code + dimensions + partitions (§9.1)."""
    tag = (0 << 0) | (0 << 1) | (1 << 4) | (len(part1) << 5)
    out = bytearray(struct.pack("<I", tag)[:3])
    out += b"\x9d\x01\x2a"
    out += struct.pack("<HH", width & 0x3FFF, height & 0x3FFF)
    out += part1
    out += part2
    return bytes(out)


def ivf_header(width: int, height: int, fps: int, n_frames: int) -> bytes:
    return (b"DKIF" + struct.pack("<HH4sHHIII", 0, 32, b"VP80",
                                  width, height, fps, 1, n_frames)
            + b"\0\0\0\0")


def ivf_frame_header(size: int, pts: int) -> bytes:
    return struct.pack("<IQ", size, pts)
