"""VP8 entropy tables recovered from the system libvpx binary.

RFC 6386's default probability tables (~2.2 KB of constants) are not
reproducible from first principles, and the spec text is not available
offline — but libvpx (the reference implementation, shipped in this
image as ``libvpx.so.7``) carries them in ``.rodata``.  They are located
structurally, not by fixed offsets:

- ``dc_qlookup``/``ac_qlookup``: the only monotone nondecreasing 128-long
  int32 arrays starting at 4 and ending at 157 / 284.
- token extra-bit probabilities (Pcat1..6): anchored on the unique
  Pcat6 byte string, which the linker lays out Pcat6..Pcat1 descending.
- ``kf_ymode_prob``/``kf_uv_mode_prob``: unique joint byte string.
- ``default_coef_probs`` [4][8][3][11]: anchored on its leading 33-byte
  run of 128s (block-type-0 band 0 is unused by construction) with a
  no-zero-bytes body, near the Pcat anchor.
- ``coef_update_probs`` [4][8][3][11]: the 255-dominated 1056-byte
  window that ends where the 255 run stops, near the Pcat anchor.

The recovered set is **validated end-to-end** before first use: the
encoder encodes a frame with these tables and the libvpx *decoder*
(``native/vpx.py``) must reproduce our reconstruction byte-exactly —
every one of the 1056+1056 coefficients is exercised by the header's
"no update" flags and the DCT token coding (``models/vp8.py`` does this
round-trip in its self-test and the test suite).
"""

from __future__ import annotations

import ctypes.util
import dataclasses
import logging
import os
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)

__all__ = ["Vp8Tables", "load_tables"]

# fixed tree/band structures (RFC 6386 §8.2, §13.2-13.3 — structural,
# not probability data; stable across every VP8 implementation)
ZIGZAG = np.array([0, 1, 4, 8, 5, 2, 3, 6, 9, 12, 13, 10, 7, 11, 14, 15])
COEF_BANDS = np.array([0, 1, 2, 3, 6, 4, 5, 6, 6, 6, 6, 6, 6, 6, 6, 7])

# token tree (11 internal nodes <-> 11 probs per context)
#   leaves: 0..4 literal, cat1..cat6, EOB
TOKEN_EOB, TOKEN_0, TOKEN_1, TOKEN_2, TOKEN_3, TOKEN_4 = -1, 0, 1, 2, 3, 4
CAT_BASE = [5, 7, 11, 19, 35, 67]            # cat1..cat6 value ranges
CAT_BITS = [1, 2, 3, 4, 5, 11]

# kf ymode tree: {-B_PRED, 2, 4, 6, -DC, -V, -H, -TM}
# kf uv tree:    {-DC, 2, -V, 4, -H, -TM}
# (encodings for the modes this encoder emits, derived from the trees)
KF_YMODE_DC_BITS = (1, 0, 0)                 # probs [0],[1],[2]
KF_UVMODE_DC_BITS = (0,)                     # prob [0]


@dataclasses.dataclass
class Vp8Tables:
    dc_qlookup: np.ndarray          # (128,) int32
    ac_qlookup: np.ndarray          # (128,) int32
    coef_probs: np.ndarray          # (4,8,3,11) uint8
    coef_update_probs: np.ndarray   # (4,8,3,11) uint8
    pcat: list                      # [ [p..] for cat1..cat6 ]
    kf_ymode_prob: np.ndarray       # (4,) uint8
    kf_uv_mode_prob: np.ndarray     # (3,) uint8
    # interframe tables (§8.3, §17 — the vp8enc P-frame parity axis)
    mv_default: np.ndarray          # (2,19) uint8 MV component probs
    mv_update: np.ndarray           # (2,19) uint8 MV prob-update probs
    mode_contexts: np.ndarray       # (6,4) int32 mv_ref tree prob table
    subpel_half: Optional[np.ndarray]  # (6,) phase-4 six-tap (or None)


_PCAT6 = bytes([254, 254, 243, 230, 196, 177, 153, 140, 133, 130, 129])
_KF_MODE_ANCHOR = bytes([142, 114, 183, 162, 101, 204, 145, 156, 163])
# vp8_default_mv_context rows start (row then col laid out adjacently);
# the full 19-byte rows are validated structurally after anchoring.
_MVC_ROW_ANCHOR = bytes([162, 128, 225, 146])
_MVC_COL_ANCHOR = bytes([164, 128, 204, 170])
# vp8_mode_contexts[6][4] int32 anchor: first two rows
_MODECTX_ANCHOR = np.array([7, 1, 1, 143, 14, 18, 14, 107],
                           "<i4").tobytes()

# The normative phase-4 six-tap row (RFC 6386 §6; one canonical form).
# Single source of truth: the rodata signature search AND the fallback
# taps for the chroma half-sample MC both use this constant.
SUBPEL_HALF_TAPS = np.array([3, -16, 77, 77, -16, 3], np.int32)

# vp8_sub_pel_filters[8][6] — the FULL normative six-tap bank (RFC 6386
# §6.3 filter.c), one row per eighth-pel phase.  Luma quarter-pel motion
# uses the even phases {0, 2, 4, 6}; chroma (eighth-chroma-pel) uses all
# eight.  Phase 4 IS SUBPEL_HALF_TAPS (asserted below), so the recovered-
# table consistency check of load_tables covers this bank's anchor row.
SUBPEL_FILTERS = np.array([
    [0, 0, 128, 0, 0, 0],
    [0, -6, 123, 12, -1, 0],
    [2, -11, 108, 36, -8, 1],
    [0, -9, 93, 50, -6, 0],
    [3, -16, 77, 77, -16, 3],
    [0, -6, 50, 93, -9, 0],
    [1, -8, 36, 108, -11, 2],
    [0, -1, 12, 123, -6, 0],
], np.int32)
assert (SUBPEL_FILTERS[4] == SUBPEL_HALF_TAPS).all()
assert (SUBPEL_FILTERS.sum(axis=1) == 128).all()

# vp8_mv_update_probs[2][19] — fixed by RFC 6386 §17.2 (entropymv.c),
# so this constant is used DIRECTLY (no rodata recovery to get wrong);
# load_tables warns when a libvpx lacks these bytes verbatim, purely as
# a layout-drift canary for the tables that ARE recovered.
MV_UPDATE_PROBS = np.array([
    [237, 246, 253, 253, 254, 254, 254, 254, 254,
     254, 254, 254, 254, 254, 250, 250, 252, 254, 254],
    [231, 243, 245, 253, 254, 254, 254, 254, 254,
     254, 254, 254, 254, 254, 251, 251, 254, 254, 254]], np.uint8)

_cached: Optional[Vp8Tables] = None


def _find_qlookup(data: bytes, last: int) -> np.ndarray:
    a = np.frombuffer(data[: len(data) // 4 * 4], np.int32).astype(np.int64)
    nd = np.diff(a) >= 0
    starts = np.flatnonzero((a[:-127] == 4) & (a[127:] == last))
    for s in starts:
        if nd[s:s + 127].all():
            return a[s:s + 128].astype(np.int32)
    raise RuntimeError(f"qlookup ending {last} not found in libvpx")


def _libvpx_path() -> str:
    for cand in (ctypes.util.find_library("vpx"), "libvpx.so.7",
                 "/lib/x86_64-linux-gnu/libvpx.so.7"):
        if not cand:
            continue
        for prefix in ("", "/lib/x86_64-linux-gnu/", "/usr/lib/",
                       "/usr/lib/x86_64-linux-gnu/"):
            p = cand if os.path.isabs(cand) else prefix + cand
            real = os.path.realpath(p)
            if os.path.exists(real):
                return real
    from ..utils.librecovery import candidate_paths
    for p in candidate_paths(stems=("vpx",)):
        if os.path.exists(p):
            return os.path.realpath(p)
    raise RuntimeError(
        "libvpx shared object not found (install libvpx / ffmpeg; see "
        "deploy/Dockerfile)")


def load_tables() -> Vp8Tables:
    """Extract (and memoize) the VP8 tables from the system libvpx."""
    global _cached
    if _cached is not None:
        return _cached
    data = open(_libvpx_path(), "rb").read()

    dc_q = _find_qlookup(data, 157)
    ac_q = _find_qlookup(data, 284)

    p6 = data.find(_PCAT6)
    if p6 < 0:
        raise RuntimeError("Pcat6 anchor not found in libvpx")
    run = data[p6:p6 + 26]
    pcat = [[run[25]], list(run[23:25]), list(run[20:23]),
            list(run[16:20]), list(run[11:16]), list(run[0:11])]

    km = data.find(_KF_MODE_ANCHOR)
    if km < 0:
        raise RuntimeError("kf mode prob anchor not found in libvpx")
    kf_uv = np.frombuffer(data[km:km + 3], np.uint8)
    kf_y = np.frombuffer(data[km + 6:km + 10], np.uint8)

    # default_coef_probs: leading 33x 128 run, zero-free 1056-byte body,
    # within +-64 KB of the Pcat anchor
    lo, hi = max(0, p6 - 0x10000), min(len(data), p6 + 0x10000)
    coef = None
    pos = lo
    pat = b"\x80" * 33
    while True:
        pos = data.find(pat, pos, hi)
        if pos < 0:
            break
        body = data[pos:pos + 1056]
        if len(body) == 1056 and 0 not in body and data[pos + 33] != 0x80:
            coef = np.frombuffer(body, np.uint8).reshape(4, 8, 3, 11)
            break
        pos += 1
    if coef is None:
        raise RuntimeError("default_coef_probs not found in libvpx")

    # coef_update_probs: 255-dominated window; find the end of the long
    # >=250 run in the cluster, take the 1056 bytes before it
    arr = np.frombuffer(data[lo:hi], np.uint8)
    dense = arr >= 230
    csum = np.cumsum(dense.astype(np.int64))
    upd = None
    ends = np.flatnonzero((arr[:-1] >= 250) & (arr[1:] < 230)) + 1
    for e in ends[::-1] if len(ends) else []:
        s = e - 1056
        if s < 0:
            continue
        if csum[e - 1] - (csum[s - 1] if s else 0) >= 950:
            window = arr[s:e]
            if (window > 0).all():
                upd = window.reshape(4, 8, 3, 11).copy()
                break
    if upd is None:
        raise RuntimeError("coef_update_probs not found in libvpx")

    # -- interframe tables -------------------------------------------
    # vp8_default_mv_context[2][19]: row and col laid out consecutively;
    # both rows have sign prob 128 at [1] and end 254,254 (long-bit
    # tails), every entry nonzero.
    mr = data.find(_MVC_ROW_ANCHOR)
    if mr < 0 or data.find(_MVC_COL_ANCHOR, mr, mr + 64) != mr + 19:
        raise RuntimeError("default MV context not found in libvpx")
    mv_default = np.frombuffer(data[mr:mr + 38], np.uint8).reshape(2, 19)
    if not ((mv_default[:, 1] == 128).all() and (mv_default > 0).all()
            and (mv_default[:, 17:] == 254).all()):
        raise RuntimeError("default MV context failed validation")

    # vp8_mv_update_probs[2][19] is FIXED by the spec (RFC 6386 §17.2 /
    # entropymv.c), so the normative constant IS the table — no
    # recovery needed, and no statistical 254-dominated scan that could
    # match a misaligned window on exotic rodata and silently desync the
    # bool decoder on every interframe (ADVICE round 5).  The rodata
    # search survives only as a sanity check: a libvpx that does not
    # carry the normative bytes anywhere gets flagged, not guessed at.
    mv_update = MV_UPDATE_PROBS.copy()
    if data.find(MV_UPDATE_PROBS.tobytes()) < 0:
        log.warning(
            "vp8_mv_update_probs: this libvpx does not contain the "
            "RFC 6386 normative table verbatim; using the spec values")

    mc = data.find(_MODECTX_ANCHOR)
    if mc < 0:
        raise RuntimeError("vp8_mode_contexts not found in libvpx")
    mode_ctx = np.frombuffer(data[mc:mc + 4 * 24], "<i4").reshape(6, 4)
    if not ((mode_ctx > 0) & (mode_ctx < 256)).all():
        raise RuntimeError("vp8_mode_contexts failed validation")

    # phase-4 (half-pel) six-tap filter row: symmetric, taps sum to 128;
    # search both int16 and int32 layouts.  Consumed by the inter
    # coder's chroma half-sample MC (models/vp8._halfpel_chroma_planes);
    # recovery is best-effort — on an exotic libvpx build that stores
    # the base tables differently the consumer falls back to
    # SUBPEL_HALF_TAPS (the RFC 6386 constant the signature searches
    # for), so VP8 serving never breaks on this.
    subpel_half = None
    for dt in ("<i2", "<i4"):
        sig = np.asarray(SUBPEL_HALF_TAPS, dt).tobytes()
        if data.find(sig) >= 0:
            subpel_half = SUBPEL_HALF_TAPS.copy()
            break

    _cached = Vp8Tables(dc_q, ac_q, coef.copy(), upd, pcat,
                        kf_y.copy(), kf_uv.copy(),
                        mv_default.copy(), mv_update, mode_ctx.copy(),
                        subpel_half)
    return _cached
