"""Bitstream construction: bit writers, entropy coders, containers.

This is the sequential tail of the encode path — the one stage that stays on
the host CPU (SURVEY.md §7 hard part #1: entropy coding's inherent serialism
on a vector machine).  Python implementations here are the reference/fallback;
:mod:`..native` provides the C++ fast path with byte-identical output.
"""

from .bitwriter import BitWriter  # noqa: F401
