"""H.264 CABAC picture assembly (pure-Python reference).

Consumes the same quantized level tensors as the CAVLC layer
(:mod:`.h264_entropy`) and emits one CABAC slice per macroblock row —
entropy_coding_mode_flag=1 streams for the Main-profile parity axis
(reference Dockerfile:210, nvh264enc's default).  The slice-per-row
structure keeps rows independently codable: each row re-inits its
arithmetic engine, so the C++ twin can code rows on a thread pool.
"""

from __future__ import annotations

import numpy as np

from . import h264 as syn
from .bitwriter import BitWriter
from .cabac import _BLK_XY, CabacEncoder, SliceCoder, _MbCtx


def _native_tables(table_idx: int):
    from .cabac_tables import context_init_tables, engine_tables
    rng, tmps, tlps = engine_tables()
    ctx = np.ascontiguousarray(context_init_tables()[table_idx], np.int8)
    return (ctx, np.ascontiguousarray(rng, np.uint8),
            np.ascontiguousarray(tmps, np.uint8),
            np.ascontiguousarray(tlps, np.uint8))


# Per-frame output buffers, reused across calls (60 fps hot path; keyed
# by geometry so a resize reallocates once).  THREAD-LOCAL: concurrent
# sessions each run their own encode thread, and the ctypes call writes
# into the buffer with the GIL released — a shared buffer would let two
# frames scribble over each other.
_TLS = __import__("threading").local()


def _native_slices(symbol: str, table_idx: int, arrays, nr, nc_mb, qp):
    """Per-row slice payloads from the C++ twin, or None (fallback).

    On a cap overflow (pathological low-qp rows) retries once at 4x
    before logging and falling back — the Python coder is ~100x slower,
    so a silent per-frame fallback would be a latency cliff."""
    import logging

    from ..native import lib as native_lib
    if not native_lib.has_cabac():
        return None
    fn = getattr(native_lib.get_lib(), symbol)
    ctx, rng, tmps, tlps = _native_tables(table_idx)
    cache = getattr(_TLS, "bufs", None)
    if cache is None:
        cache = _TLS.bufs = {}
    for scale in (1, 4):
        cap = (2048 + nc_mb * 1536) * scale
        key = (symbol, nr, cap)
        out = cache.get(key)
        if out is None:
            if len(cache) > 8:
                cache.clear()
            out = cache[key] = np.empty(nr * cap, np.uint8)
        lens = np.zeros(nr, np.int64)
        rc = fn(*arrays, nr, nc_mb, int(qp), ctx, rng, tmps, tlps,
                out, lens, cap)
        if rc == 0:
            return [out[r * cap:r * cap + lens[r]].tobytes()
                    for r in range(nr)]
    logging.getLogger(__name__).warning(
        "native CABAC row overflow at 4x cap; falling back to the "
        "Python coder for this picture")
    return None


def _native_intra_payloads(luma_dc, luma_ac, cb_dc, cb_ac, cr_dc, cr_ac,
                           pred_mode, mb_i4, i4_modes, luma_i4, qp):
    nr, nc_mb = luma_dc.shape[:2]
    c = np.ascontiguousarray
    return _native_slices(
        "h264_cabac_intra_slices", 0,
        (c(luma_dc, np.int32), c(luma_ac, np.int32),
         c(cb_dc, np.int32), c(cb_ac, np.int32),
         c(cr_dc, np.int32), c(cr_ac, np.int32),
         c(pred_mode, np.int32), c(mb_i4, np.uint8),
         c(i4_modes, np.int32), c(luma_i4, np.int32)),
        nr, nc_mb, qp)


def _native_p_payloads(mv, luma, cb_dc, cb_ac, cr_dc, cr_ac, qp,
                       cabac_init_idc):
    nr, nc_mb = luma.shape[:2]
    c = np.ascontiguousarray
    return _native_slices(
        "h264_cabac_p_slices", 1 + cabac_init_idc,
        (c(mv, np.int32), c(luma, np.int32),
         c(cb_dc, np.int32), c(cb_ac, np.int32),
         c(cr_dc, np.int32), c(cr_ac, np.int32)),
        nr, nc_mb, qp)


def _engine_rows(buf: np.ndarray, nr: int, nc_mb: int, table_idx: int,
                 qp: int):
    """Replay a device-binarized record stream (ops/cabac_binarize wire
    format) through the arithmetic engine: native C rows when built,
    else the pure-Python engine.  Returns per-row slice payloads, or
    None on the transport's overflow flag (caller goes dense)."""
    from ..native import lib as native_lib
    from ..ops import cabac_binarize

    split = cabac_binarize.split_rows(buf, nr)
    if split is None:
        return None
    payload, row_off, row_bits = split
    if native_lib.has_cabac_engine():
        import logging
        ctx, rng, tmps, tlps = _native_tables(table_idx)
        for scale in (1, 4):
            cap = (2048 + nc_mb * 1536) * scale
            rows = native_lib.cabac_engine_rows(
                payload, row_off, row_bits, nr, qp, ctx, rng, tmps,
                tlps, cap)
            if isinstance(rows, list):
                return rows
            if rows == -2:
                # malformed record stream: a bigger output cap cannot
                # help — name the real failure instead of retrying
                logging.getLogger(__name__).warning(
                    "device-binarized CABAC record stream malformed "
                    "(engine bit-count mismatch); dense fallback")
                return None
        logging.getLogger(__name__).warning(
            "native CABAC engine overflow at 4x cap; dense fallback")
        return None
    # Python engine fallback: decode records, drive CabacEncoder
    out = []
    for r in range(nr):
        recs = cabac_binarize.decode_records_py(
            payload[row_off[r]:row_off[r + 1]], int(row_bits[r]))
        enc = CabacEncoder(table_idx, qp)
        for rec in recs:
            kind = rec[0]
            if kind == "dec":
                enc.decision(rec[1], rec[2])
            elif kind == "run":
                for _ in range(rec[2]):
                    enc.decision(rec[1], 1)
            elif kind == "byp":
                for b in rec[1]:
                    enc.bypass(b)
            else:
                enc.terminate(rec[1])
        out.append(enc.get_bytes())
    return out


def encode_intra_from_binstream(buf: np.ndarray, *, nr: int, nc_mb: int,
                                qp: int, frame_num: int = 0,
                                idr_pic_id: int = 0, sps: bytes = b"",
                                pps: bytes = b"",
                                with_headers: bool = True,
                                qp_delta: int = 0,
                                deblocking_idc: int = 1):
    """IDR access unit from a device-binarized record stream, or None
    when the transport flagged overflow (caller re-encodes dense)."""
    payloads = _engine_rows(buf, nr, nc_mb, 0, qp)
    if payloads is None:
        return None
    out = bytearray()
    if with_headers:
        out += syn.nal_unit(syn.NAL_SPS, sps)
        out += syn.nal_unit(syn.NAL_PPS, pps)
    for my, pl in enumerate(payloads):
        bw = BitWriter()
        syn.slice_header(bw, first_mb=my * nc_mb, slice_type=7,
                         frame_num=frame_num, idr=True,
                         idr_pic_id=idr_pic_id, qp_delta=qp_delta,
                         deblocking_idc=deblocking_idc, cabac=True)
        bw.pad_to_byte(1)
        out += syn.nal_unit(syn.NAL_IDR, bw.getvalue() + pl)
    return bytes(out)


def encode_p_from_binstream(buf: np.ndarray, *, nr: int, nc_mb: int,
                            qp: int, frame_num: int, qp_delta: int = 0,
                            deblocking_idc: int = 1,
                            cabac_init_idc: int = 0):
    """P access unit from a device-binarized record stream, or None on
    the transport overflow flag."""
    payloads = _engine_rows(buf, nr, nc_mb, 1 + cabac_init_idc, qp)
    if payloads is None:
        return None
    out = bytearray()
    for my, pl in enumerate(payloads):
        bw = BitWriter()
        syn.slice_header(bw, first_mb=my * nc_mb, slice_type=5,
                         frame_num=frame_num, idr=False,
                         qp_delta=qp_delta,
                         deblocking_idc=deblocking_idc, cabac=True,
                         cabac_init_idc=cabac_init_idc)
        bw.pad_to_byte(1)
        out += syn.nal_unit(syn.NAL_SLICE, bw.getvalue() + pl,
                            ref_idc=2)
    return bytes(out)


def _prep_common(cb_dc, cb_ac, cr_dc, cr_ac):
    nr, nc_mb = cb_dc.shape[:2]
    chroma_ac_any = cb_ac.any(axis=(2, 3)) | cr_ac.any(axis=(2, 3))
    chroma_dc_any = cb_dc.any(axis=2) | cr_dc.any(axis=2)
    cbp_chroma = np.where(chroma_ac_any, 2,
                          np.where(chroma_dc_any, 1, 0))
    return cbp_chroma


def _code_chroma(sc: SliceCoder, cc: int, cb_dc, cr_dc, cb_ac, cr_ac,
                 ctx: _MbCtx, intra: bool) -> None:
    """Chroma residuals (DC cat3, AC cat4) + left-ctx bookkeeping."""
    if cc > 0:
        inc = sc.cbf_inc_dc("cbf_cb_dc", intra)
        ctx.cbf_cb_dc = sc.residual(cb_dc, 3, inc)
        inc = sc.cbf_inc_dc("cbf_cr_dc", intra)
        ctx.cbf_cr_dc = sc.residual(cr_dc, 3, inc)
    if cc == 2:
        for comp, (ac, grid, attr) in enumerate(
                ((cb_ac, ctx.cbf_cb, "cbf_cb"),
                 (cr_ac, ctx.cbf_cr, "cbf_cr"))):
            for b in range(4):
                by, bx = divmod(b, 2)
                inc = sc.cbf_inc_chroma(grid, attr, bx, by, intra)
                grid[by][bx] = sc.residual(ac[b], 4, inc)


def encode_intra_picture(levels: dict, *, qp: int,
                         frame_num: int = 0, idr_pic_id: int = 0,
                         sps: bytes = b"", pps: bytes = b"",
                         with_headers: bool = True,
                         qp_delta: int = 0,
                         deblocking_idc: int = 1,
                         use_native: bool = True,
                         qp_map=None) -> bytes:
    """Assemble a CABAC IDR access unit from device-stage level tensors.

    ``qp`` is SliceQPy (context init depends on it, spec 9.3.1.1) —
    pic_init_qp + qp_delta as signaled.

    ``qp_map`` (tune=hq): (R, C) absolute per-MB qp; mb_qp_delta chains
    from ``qp`` per row via the SliceCoder's ctx-60/61 machinery.  The
    native C++ coder has no qp plumbing, so a qp_map forces the Python
    coder.
    """
    luma_dc = np.asarray(levels["luma_dc"])   # (R, C, 16) zigzag
    luma_ac = np.asarray(levels["luma_ac"])   # (R, C, 16, 15)
    cb_dc = np.asarray(levels["cb_dc"])
    cb_ac = np.asarray(levels["cb_ac"])
    cr_dc = np.asarray(levels["cr_dc"])
    cr_ac = np.asarray(levels["cr_ac"])
    nr, nc_mb = luma_dc.shape[:2]
    pred_mode = np.asarray(levels.get(
        "pred_mode", np.full((nr, nc_mb), 2, np.int32)))
    mb_i4 = np.asarray(levels.get("mb_i4", np.zeros((nr, nc_mb), bool)))
    i4_modes = np.asarray(levels.get(
        "i4_modes", np.full((nr, nc_mb, 16), 2, np.int32)))
    luma_i4 = np.asarray(levels.get(
        "luma_i4", np.zeros((nr, nc_mb, 16, 16), np.int32)))

    def _headers():
        o = bytearray()
        if with_headers:
            o += syn.nal_unit(syn.NAL_SPS, sps)
            o += syn.nal_unit(syn.NAL_PPS, pps)
        return o

    def _slice_hdr(my):
        bw = BitWriter()
        syn.slice_header(bw, first_mb=my * nc_mb, slice_type=7,
                         frame_num=frame_num, idr=True,
                         idr_pic_id=idr_pic_id, qp_delta=qp_delta,
                         deblocking_idc=deblocking_idc, cabac=True)
        bw.pad_to_byte(1)                 # cabac_alignment_one_bit
        return bw.getvalue()

    if use_native and qp_map is None:
        payloads = _native_intra_payloads(
            luma_dc, luma_ac, cb_dc, cb_ac, cr_dc, cr_ac,
            pred_mode, mb_i4, i4_modes, luma_i4, qp)
        if payloads is not None:
            out = _headers()
            for my, pl in enumerate(payloads):
                out += syn.nal_unit(syn.NAL_IDR, _slice_hdr(my) + pl)
            return bytes(out)

    cbp_luma16 = luma_ac.any(axis=(2, 3))                 # I16 AC flag
    i4_grp_any = luma_i4.reshape(nr, nc_mb, 4, 4, 16).any(axis=(3, 4))
    cbp_luma4 = (i4_grp_any * (1 << np.arange(4))).sum(axis=2)
    cbp_chroma = _prep_common(cb_dc, cb_ac, cr_dc, cr_ac)

    # Intra4x4PredMode predictors (8.3.1.1) — same derivation as the
    # CAVLC layer: A crosses into the left MB, B only within the MB.
    modes_r = np.full((nr, nc_mb, 4, 4), 2, np.int32)
    for blk, (bx, by) in enumerate(_BLK_XY):
        modes_r[:, :, by, bx] = np.where(mb_i4, i4_modes[:, :, blk], 2)
    mode_a = np.full((nr, nc_mb, 4, 4), 2, np.int32)
    a_avail = np.zeros((nr, nc_mb, 4, 4), bool)
    mode_a[:, :, :, 1:] = modes_r[:, :, :, :-1]
    a_avail[:, :, :, 1:] = True
    mode_a[:, 1:, :, 0] = modes_r[:, :-1, :, 3]
    a_avail[:, 1:, :, 0] = True
    mode_b = np.full((nr, nc_mb, 4, 4), 2, np.int32)
    b_avail = np.zeros((nr, nc_mb, 4, 4), bool)
    mode_b[:, :, 1:, :] = modes_r[:, :, :-1, :]
    b_avail[:, :, 1:, :] = True
    pred_i4 = np.where(a_avail & b_avail,
                       np.minimum(mode_a, mode_b), 2)

    out = _headers()

    for my in range(nr):
        enc = CabacEncoder(0, qp)
        sc = SliceCoder(enc, intra_slice=True)
        prev_qp = qp                          # mb_qp_delta row anchor
        for mx in range(nc_mb):
            cc = int(cbp_chroma[my, mx])
            ctx = _MbCtx()
            ctx.intra = True
            if mb_i4[my, mx]:
                cl4 = int(cbp_luma4[my, mx])
                sc.mb_type_i(True, 0, False, 0)
                for blk, (bx, by) in enumerate(_BLK_XY):
                    sc.i4_pred_mode(int(i4_modes[my, mx, blk]),
                                    int(pred_i4[my, mx, by, bx]))
                sc.intra_chroma_mode(0)
                sc.cbp(cl4, cc)
                if cl4 or cc:
                    if qp_map is None:
                        sc.qp_delta(0)
                    else:
                        q = int(qp_map[my, mx])
                        sc.qp_delta(q - prev_qp)
                        prev_qp = q
                else:
                    sc.qp_delta_absent()
                for blk, (bx, by) in enumerate(_BLK_XY):
                    if cl4 & (1 << (blk // 4)):
                        inc = sc.cbf_inc_luma(ctx.cbf_luma, bx, by, True)
                        ctx.cbf_luma[by][bx] = sc.residual(
                            luma_i4[my, mx, blk], 2, inc)
                _code_chroma(sc, cc, cb_dc[my, mx], cr_dc[my, mx],
                             cb_ac[my, mx], cr_ac[my, mx], ctx, True)
                ctx.i16 = False
                ctx.modes = modes_r[my, mx]
                ctx.cbp_luma = cl4
            else:
                cl = bool(cbp_luma16[my, mx])
                sc.mb_type_i(False, int(pred_mode[my, mx]), cl, cc)
                sc.intra_chroma_mode(0)
                if qp_map is None:
                    sc.qp_delta(0)
                else:                         # I16 always codes the syntax
                    q = int(qp_map[my, mx])
                    sc.qp_delta(q - prev_qp)
                    prev_qp = q
                inc = sc.cbf_inc_dc("cbf_luma_dc", True, require_i16=True)
                ctx.cbf_luma_dc = sc.residual(luma_dc[my, mx], 0, inc)
                if cl:
                    for blk, (bx, by) in enumerate(_BLK_XY):
                        inc = sc.cbf_inc_luma(ctx.cbf_luma, bx, by, True)
                        ctx.cbf_luma[by][bx] = sc.residual(
                            luma_ac[my, mx, blk], 1, inc)
                _code_chroma(sc, cc, cb_dc[my, mx], cr_dc[my, mx],
                             cb_ac[my, mx], cr_ac[my, mx], ctx, True)
                ctx.i16 = True
                ctx.cbp_luma = 0xF if cl else 0
            ctx.cbp_chroma = cc
            sc.left = ctx
            sc.end_of_slice(mx == nc_mb - 1)
        out += syn.nal_unit(syn.NAL_IDR, _slice_hdr(my) + enc.get_bytes())
    return bytes(out)


def encode_p_picture(levels: dict, *, qp: int, frame_num: int,
                     qp_delta: int = 0, deblocking_idc: int = 1,
                     cabac_init_idc: int = 0,
                     use_native: bool = True,
                     qp_map=None) -> bytes:
    """Assemble a CABAC P access unit (P_L0_16x16 + P_Skip subset).

    MV prediction matches the CAVLC layer: under slice-per-row, mvp is
    the left MB's MV and P_Skip requires mv == (0,0) (h264_entropy
    encode_p_picture docstring).  ``qp_map`` (tune=hq): per-MB qp, as in
    :func:`encode_intra_picture` — forces the Python coder.
    """
    mv = np.asarray(levels["mv"], np.int32)       # (R, C, 2) (y, x) qpel
    luma = np.asarray(levels["luma"], np.int32)   # (R, C, 16, 16) zigzag
    cb_dc = np.asarray(levels["cb_dc"], np.int32)
    cb_ac = np.asarray(levels["cb_ac"], np.int32)
    cr_dc = np.asarray(levels["cr_dc"], np.int32)
    cr_ac = np.asarray(levels["cr_ac"], np.int32)
    nr, nc_mb = luma.shape[:2]

    luma8x8_any = luma.reshape(nr, nc_mb, 4, 4, 16).any(axis=(3, 4))
    cbp_luma = (luma8x8_any * (1 << np.arange(4))).sum(axis=2)
    cbp_chroma = _prep_common(cb_dc, cb_ac, cr_dc, cr_ac)
    cbp = cbp_luma + 16 * cbp_chroma
    skip = (mv == 0).all(axis=2) & (cbp == 0)

    def _slice_hdr(my):
        bw = BitWriter()
        syn.slice_header(bw, first_mb=my * nc_mb, slice_type=5,
                         frame_num=frame_num, idr=False,
                         qp_delta=qp_delta, deblocking_idc=deblocking_idc,
                         cabac=True, cabac_init_idc=cabac_init_idc)
        bw.pad_to_byte(1)                 # cabac_alignment_one_bit
        return bw.getvalue()

    if use_native and qp_map is None:
        payloads = _native_p_payloads(mv, luma, cb_dc, cb_ac, cr_dc, cr_ac,
                                      qp, cabac_init_idc)
        if payloads is not None:
            out = bytearray()
            for my, pl in enumerate(payloads):
                out += syn.nal_unit(syn.NAL_SLICE, _slice_hdr(my) + pl,
                                    ref_idc=2)
            return bytes(out)

    out = bytearray()
    for my in range(nr):
        enc = CabacEncoder(1 + cabac_init_idc, qp)
        sc = SliceCoder(enc, intra_slice=False)
        prev_qp = qp                          # mb_qp_delta row anchor
        mvp = np.zeros(2, np.int32)
        for mx in range(nc_mb):
            ctx = _MbCtx()
            if skip[my, mx]:
                sc.mb_skip(True)
                sc.qp_delta_absent()
                ctx.skip = True
                mvp = np.zeros(2, np.int32)
                sc.left = ctx
                sc.end_of_slice(mx == nc_mb - 1)
                continue
            sc.mb_skip(False)
            sc.mb_type_p16()
            mvd = mv[my, mx] - mvp
            sc.mvd(0, int(mvd[1]))        # x component
            sc.mvd(1, int(mvd[0]))        # y component
            ctx.abs_mvd = np.abs(mvd)[::-1].copy()   # (x, y) order
            mvp = mv[my, mx].copy()
            cl = int(cbp_luma[my, mx])
            cc = int(cbp_chroma[my, mx])
            sc.cbp(cl, cc)
            if cl or cc:
                if qp_map is None:
                    sc.qp_delta(0)
                else:
                    q = int(qp_map[my, mx])
                    sc.qp_delta(q - prev_qp)
                    prev_qp = q
            else:
                sc.qp_delta_absent()
            for blk, (bx, by) in enumerate(_BLK_XY):
                if cl & (1 << (blk // 4)):
                    inc = sc.cbf_inc_luma(ctx.cbf_luma, bx, by, False)
                    ctx.cbf_luma[by][bx] = sc.residual(
                        luma[my, mx, blk], 2, inc)
            _code_chroma(sc, cc, cb_dc[my, mx], cr_dc[my, mx],
                         cb_ac[my, mx], cr_ac[my, mx], ctx, False)
            ctx.cbp_luma = cl
            ctx.cbp_chroma = cc
            sc.left = ctx
            sc.end_of_slice(mx == nc_mb - 1)
        out += syn.nal_unit(syn.NAL_SLICE, _slice_hdr(my) + enc.get_bytes(),
                            ref_idc=2)
    return bytes(out)
