"""H.264 I_16x16 slice/MB entropy layer (pure-Python reference).

Consumes the quantized level tensors produced by the device stage
(:mod:`..ops.h264_device`) and emits one CAVLC slice per macroblock row —
the slice-per-row structure that legalizes the device stage's row
parallelism.  The native C++ path (``native/cavlc.cpp``) mirrors this
byte-for-byte; tests enforce equality.

nC context derivation (spec §9.2.1) is vectorized in numpy up front so the
per-block Python work is pure bit emission.
"""

from __future__ import annotations

import numpy as np

from . import h264 as syn
from .bitwriter import BitWriter
from .cavlc import encode_block

# luma4x4BlkIdx -> (bx, by); keep in sync with ops.h264_device.LUMA_BLOCK_ORDER
_BLK_XY = [(0, 0), (1, 0), (0, 1), (1, 1),
           (2, 0), (3, 0), (2, 1), (3, 1),
           (0, 2), (1, 2), (0, 3), (1, 3),
           (2, 2), (3, 2), (2, 3), (3, 3)]


def _nc_grid(tc, left_from_prev_mb):
    """Vectorized nC for a (R, C, B, B) per-block total_coeff array.

    B = 4 (luma) or 2 (chroma).  Above-neighbor exists only within the MB
    (the MB above is in another slice); left-neighbor crosses into the
    previous MB's rightmost column of blocks.
    """
    r, c, b, _ = tc.shape
    na = np.zeros_like(tc)
    na_avail = np.zeros(tc.shape, bool)
    na[:, :, :, 1:] = tc[:, :, :, :-1]
    na_avail[:, :, :, 1:] = True
    na[:, 1:, :, 0] = left_from_prev_mb[:, :-1]
    na_avail[:, 1:, :, 0] = True
    nb = np.zeros_like(tc)
    nb_avail = np.zeros(tc.shape, bool)
    nb[:, :, 1:, :] = tc[:, :, :-1, :]
    nb_avail[:, :, 1:, :] = True
    both = na_avail & nb_avail
    nc = np.where(both, (na + nb + 1) >> 1,
                  np.where(na_avail, na, np.where(nb_avail, nb, 0)))
    return nc.astype(np.int32)


# Table 9-4: coded_block_pattern me(v) mapping, Inter column:
# _CBP_INTER_CODENUM[cbp] = codeNum to write.
_CBP_INTER_TO_CODENUM = np.zeros(48, np.int32)
_CBP_INTER_BY_CODENUM = [
    0, 16, 1, 2, 4, 8, 32, 3, 5, 10, 12, 15, 47, 7, 11, 13,
    14, 6, 9, 31, 35, 37, 42, 44, 33, 34, 36, 40, 39, 43, 45, 46,
    17, 18, 20, 24, 19, 21, 26, 28, 23, 27, 29, 30, 22, 25, 38, 41]
for _cn, _cbp in enumerate(_CBP_INTER_BY_CODENUM):
    _CBP_INTER_TO_CODENUM[_cbp] = _cn

# Table 9-4, Intra_4x4 column: _CBP_INTRA_TO_CODENUM[cbp] = codeNum.
_CBP_INTRA_TO_CODENUM = np.zeros(48, np.int32)
_CBP_INTRA_BY_CODENUM = [
    47, 31, 15, 0, 23, 27, 29, 30, 7, 11, 13, 14, 39, 43, 45, 46,
    16, 3, 5, 10, 12, 19, 21, 26, 28, 35, 37, 42, 44, 1, 2, 4,
    8, 17, 18, 20, 24, 6, 9, 22, 25, 32, 33, 34, 36, 40, 38, 41]
assert sorted(_CBP_INTRA_BY_CODENUM) == list(range(48))
for _cn, _cbp in enumerate(_CBP_INTRA_BY_CODENUM):
    _CBP_INTRA_TO_CODENUM[_cbp] = _cn


def p_mean_coded_qp(levels: dict, qp_map, slice_qp: int) -> float:
    """Mean EFFECTIVE per-MB qp of a P frame under ``qp_map`` — the
    spec-7.4.5 chain the emitted mb_qp_delta syntax realizes (an MB
    with no syntax carries the previous coded qp).  The device CAVLC
    meta word sums exactly this chain (ops/cavlc_p_device), so host
    fallbacks MUST report the same statistic or the RateController's
    +6-qp-halves-bits normalization jitters between paths."""
    from ..ops.aq import qp_chain_np

    luma = np.asarray(levels["luma"], np.int32)
    cb_dc = np.asarray(levels["cb_dc"], np.int32)
    cb_ac = np.asarray(levels["cb_ac"], np.int32)
    cr_dc = np.asarray(levels["cr_dc"], np.int32)
    cr_ac = np.asarray(levels["cr_ac"], np.int32)
    nr, nc_mb = luma.shape[:2]
    codes = (luma.any(axis=(2, 3)) | cb_dc.any(axis=2)
             | cb_ac.any(axis=(2, 3)) | cr_dc.any(axis=2)
             | cr_ac.any(axis=(2, 3)))
    mb_intra = np.asarray(levels.get(
        "mb_intra", np.zeros((nr, nc_mb), bool)), bool)
    codes = codes | mb_intra          # I_16x16 always codes mb_qp_delta
    eff, _ = qp_chain_np(np.asarray(qp_map, np.int32), codes,
                         int(slice_qp))
    return float(eff.mean())


def intra_mean_coded_qp(levels: dict, qp_map, slice_qp: int) -> float:
    """Mean effective per-MB qp of an intra picture under ``qp_map``:
    I_16x16 always codes the syntax; an I_NxN MB with cbp == 0 carries
    the previous MB's qp (mirrors encode_intra_picture)."""
    from ..ops.aq import qp_chain_np

    luma_ac = np.asarray(levels["luma_ac"], np.int32)
    nr, nc_mb = luma_ac.shape[:2]
    mb_i4 = np.asarray(levels.get(
        "mb_i4", np.zeros((nr, nc_mb), bool)), bool)
    luma_i4 = np.asarray(levels.get(
        "luma_i4", np.zeros((nr, nc_mb, 16, 16), np.int32)), np.int32)
    cb_dc = np.asarray(levels["cb_dc"], np.int32)
    cb_ac = np.asarray(levels["cb_ac"], np.int32)
    cr_dc = np.asarray(levels["cr_dc"], np.int32)
    cr_ac = np.asarray(levels["cr_ac"], np.int32)
    chroma_any = (cb_dc.any(axis=2) | cb_ac.any(axis=(2, 3))
                  | cr_dc.any(axis=2) | cr_ac.any(axis=(2, 3)))
    i4_codes = luma_i4.any(axis=(2, 3)) | chroma_any
    codes = np.where(mb_i4, i4_codes, True)
    eff, _ = qp_chain_np(np.asarray(qp_map, np.int32), codes,
                         int(slice_qp))
    return float(eff.mean())


def encode_p_picture(levels: dict, *, frame_num: int,
                     qp_delta: int = 0, deblocking_idc: int = 1,
                     qp_map=None, slice_qp: int = None) -> bytes:
    """Assemble a P access unit (one P slice per MB row) from the inter
    device stage's tensors (:mod:`..ops.h264_inter`).

    MV prediction uses the slice-per-row geometry: neighbors B/C are in
    other slices (unavailable), so mvp = left MB's MV (spec §8.4.1.3) and
    P_Skip motion is always (0,0) (§8.4.1.1 with mbAddrB unavailable) —
    an MB is skippable exactly when mv == (0,0) and cbp == 0.

    ``qp_map`` (tune=hq): (R, C) absolute per-MB qp the device stage
    quantized with; mb_qp_delta chains from ``slice_qp`` per row (the MB
    above is in another slice) and is emitted only where the syntax
    exists (cbp != 0, or I_16x16 which always codes it) — an uncoded MB
    has no coefficients, so carrying the previous qp is conformant by
    construction.

    ``levels["mb_intra"]`` (tune=hq I16-in-P): (R, C) bool plus
    ``i16_dc`` (R, C, 16) / ``i16_ac`` (R, C, 16, 15) — MBs the
    Lagrangian mode decision coded I_16x16/DC inside the P slice
    (Table 7-11 mb_type >= 5).  Mirrors ops/cavlc_p_device byte-for-byte.
    """
    mv = np.asarray(levels["mv"], np.int32)         # (R, C, 2) quarter-pel
    luma = np.asarray(levels["luma"], np.int32)     # (R, C, 16, 16) zigzag
    cb_dc = np.asarray(levels["cb_dc"], np.int32)   # (R, C, 4)
    cb_ac = np.asarray(levels["cb_ac"], np.int32)   # (R, C, 4, 15)
    cr_dc = np.asarray(levels["cr_dc"], np.int32)
    cr_ac = np.asarray(levels["cr_ac"], np.int32)
    nr, nc_mb = luma.shape[:2]
    mb_intra = np.asarray(levels.get(
        "mb_intra", np.zeros((nr, nc_mb), bool)), bool)
    i16_dc = np.asarray(levels.get(
        "i16_dc", np.zeros((nr, nc_mb, 16), np.int32)), np.int32)
    i16_ac = np.asarray(levels.get(
        "i16_ac", np.zeros((nr, nc_mb, 16, 15), np.int32)), np.int32)

    # --- CBP: luma bit per 8x8 sub-block (bits 0-3), chroma 2 bits -----
    # luma4x4BlkIdx -> 8x8 quadrant: blkIdx//4 (the _BLK_XY grouping).
    luma8x8_any = luma.reshape(nr, nc_mb, 4, 4, 16).any(axis=(3, 4))
    cbp_luma = (luma8x8_any * (1 << np.arange(4))).sum(axis=2)   # (R, C)
    chroma_ac_any = cb_ac.any(axis=(2, 3)) | cr_ac.any(axis=(2, 3))
    chroma_dc_any = cb_dc.any(axis=2) | cr_dc.any(axis=2)
    cbp_chroma = np.where(chroma_ac_any, 2,
                          np.where(chroma_dc_any, 1, 0))
    cbp = cbp_luma + 16 * cbp_chroma                             # (R, C)
    cl15 = i16_ac.any(axis=(2, 3))                 # I16 luma cbp 0/15

    zero_mv = (mv == 0).all(axis=2)
    skip = zero_mv & (cbp == 0) & ~mb_intra                      # (R, C)

    # --- nC grids: per-4x4 total_coeff (16-coef blocks) ---------------
    tc_blk = np.count_nonzero(luma, axis=3)                      # (R,C,16)
    tc_blk = np.where(mb_intra[:, :, None],
                      np.count_nonzero(i16_ac, axis=3)
                      * cl15[:, :, None], tc_blk)
    tc_luma = np.zeros((nr, nc_mb, 4, 4), np.int32)
    for b, (bx, by) in enumerate(_BLK_XY):
        tc_luma[:, :, by, bx] = tc_blk[:, :, b]

    def chroma_tc(ac):
        t = np.count_nonzero(ac, axis=3) * (cbp_chroma == 2)[:, :, None]
        return t.reshape(nr, nc_mb, 2, 2).astype(np.int32)

    tc_cb, tc_cr = chroma_tc(cb_ac), chroma_tc(cr_ac)
    nc_luma = _nc_grid(tc_luma, tc_luma[:, :, :, 3])
    nc_cb = _nc_grid(tc_cb, tc_cb[:, :, :, 1])
    nc_cr = _nc_grid(tc_cr, tc_cr[:, :, :, 1])

    if qp_map is not None and slice_qp is None:
        raise ValueError("qp_map requires slice_qp")

    out = bytearray()
    for my in range(nr):
        bw = BitWriter()
        syn.slice_header(bw, first_mb=my * nc_mb, slice_type=5,
                         frame_num=frame_num, idr=False, qp_delta=qp_delta,
                         deblocking_idc=deblocking_idc)
        run = 0
        prev_qp = slice_qp                    # row-start chain anchor
        mvp = np.zeros(2, np.int32)      # A unavailable at row start -> 0
        for mx in range(nc_mb):
            if skip[my, mx]:
                run += 1
                mvp = np.zeros(2, np.int32)   # skipped MB's mv is (0,0)
                continue
            if mb_intra[my, mx]:
                # I_16x16/DC inside the P slice (tune=hq mode decision):
                # mb_type 5 + (1 + predMode(2) + 4*cbp_chroma + 12*cl),
                # DC chroma mode, mb_qp_delta ALWAYS, Intra16x16DCLevel
                # then 15-coef AC blocks when the (0/15) luma cbp is set.
                syn.write_ue(bw, run)
                run = 0
                cc = int(cbp_chroma[my, mx])
                cl = bool(cl15[my, mx])
                syn.write_ue(bw, 8 + 4 * cc + (12 if cl else 0))
                syn.write_ue(bw, 0)           # intra_chroma_pred_mode DC
                if qp_map is None:
                    syn.write_se(bw, 0)
                else:
                    q = int(qp_map[my, mx])
                    syn.write_se(bw, q - prev_qp)
                    prev_qp = q
                encode_block(bw, i16_dc[my, mx],
                             int(nc_luma[my, mx, 0, 0]), 16)
                if cl:
                    for b, (bx, by) in enumerate(_BLK_XY):
                        encode_block(bw, i16_ac[my, mx, b],
                                     int(nc_luma[my, mx, by, bx]), 15)
                cc2 = cc
                if cc2 > 0:
                    encode_block(bw, cb_dc[my, mx], -1, 4)
                    encode_block(bw, cr_dc[my, mx], -1, 4)
                if cc2 == 2:
                    for b in range(4):
                        by, bx = divmod(b, 2)
                        encode_block(bw, cb_ac[my, mx, b],
                                     int(nc_cb[my, mx, by, bx]), 15)
                    for b in range(4):
                        by, bx = divmod(b, 2)
                        encode_block(bw, cr_ac[my, mx, b],
                                     int(nc_cr[my, mx, by, bx]), 15)
                # an intra neighbor contributes the zero vector to mv
                # prediction (spec 8.4.1.3.2: intra -> unavailable -> 0)
                mvp = np.zeros(2, np.int32)
                continue
            syn.write_ue(bw, run)             # mb_skip_run
            run = 0
            syn.write_ue(bw, 0)               # mb_type: P_L0_16x16
            # device MVs are quarter-pel — mvd's native unit, (x, y)
            mvd = mv[my, mx] - mvp
            syn.write_se(bw, int(mvd[1]))     # mvd_l0 x
            syn.write_se(bw, int(mvd[0]))     # mvd_l0 y
            mvp = mv[my, mx].copy()
            syn.write_ue(bw, int(_CBP_INTER_TO_CODENUM[cbp[my, mx]]))
            if cbp[my, mx]:
                if qp_map is None:
                    syn.write_se(bw, 0)       # mb_qp_delta
                else:
                    q = int(qp_map[my, mx])
                    syn.write_se(bw, q - prev_qp)
                    prev_qp = q
                if cbp_luma[my, mx]:
                    for b, (bx, by) in enumerate(_BLK_XY):
                        if cbp_luma[my, mx] & (1 << (b // 4)):
                            encode_block(bw, luma[my, mx, b],
                                         int(nc_luma[my, mx, by, bx]), 16)
                cc = int(cbp_chroma[my, mx])
                if cc > 0:
                    encode_block(bw, cb_dc[my, mx], -1, 4)
                    encode_block(bw, cr_dc[my, mx], -1, 4)
                if cc == 2:
                    for b in range(4):
                        by, bx = divmod(b, 2)
                        encode_block(bw, cb_ac[my, mx, b],
                                     int(nc_cb[my, mx, by, bx]), 15)
                    for b in range(4):
                        by, bx = divmod(b, 2)
                        encode_block(bw, cr_ac[my, mx, b],
                                     int(nc_cr[my, mx, by, bx]), 15)
        if run:
            syn.write_ue(bw, run)             # trailing skip run
        syn.rbsp_trailing_bits(bw)
        out += syn.nal_unit(syn.NAL_SLICE, bw.getvalue(), ref_idc=2)
    return bytes(out)


def encode_intra_picture(levels: dict, *,
                         frame_num: int = 0, idr_pic_id: int = 0,
                         sps: bytes = b"", pps: bytes = b"",
                         with_headers: bool = True,
                         qp_delta: int = 0, deblocking_idc: int = 1,
                         qp_map=None, slice_qp: int = None) -> bytes:
    """Assemble a full IDR access unit from device-stage level tensors.

    Macroblocks are I_16x16 by default; where ``mb_i4`` is set the MB is
    coded I_NxN (spec 7.3.5/7.4.5): per-4x4-block prediction modes
    (``i4_modes``, signaled against the min(A, B) predictor of 8.3.1.1),
    4-bit luma CBP over 8x8 groups, and 16-coefficient LumaLevel4x4
    residual blocks (``luma_i4``) with no Hadamard DC split.

    ``qp_map``/``slice_qp`` (tune=hq): per-MB absolute qp; mb_qp_delta
    chains per row from ``slice_qp``.  I_16x16 always codes the syntax;
    an I_NxN MB with cbp == 0 carries the previous MB's qp instead
    (it also has no coefficients, so the chain stays conformant)."""
    luma_dc = np.asarray(levels["luma_dc"])   # (R, C, 16) zigzag
    luma_ac = np.asarray(levels["luma_ac"])   # (R, C, 16, 15)
    cb_dc = np.asarray(levels["cb_dc"])       # (R, C, 4)
    cb_ac = np.asarray(levels["cb_ac"])       # (R, C, 4, 15)
    cr_dc = np.asarray(levels["cr_dc"])
    cr_ac = np.asarray(levels["cr_ac"])
    nr, nc_mb = luma_dc.shape[:2]
    # Intra16x16PredMode per MB (2 = DC everywhere when absent — the
    # pre-mode-decision contract)
    pred_mode = np.asarray(levels.get(
        "pred_mode", np.full((nr, nc_mb), 2, np.int32)))
    mb_i4 = np.asarray(levels.get(
        "mb_i4", np.zeros((nr, nc_mb), bool)))
    i4_modes = np.asarray(levels.get(
        "i4_modes", np.full((nr, nc_mb, 16), 2, np.int32)))
    luma_i4 = np.asarray(levels.get(
        "luma_i4", np.zeros((nr, nc_mb, 16, 16), np.int32)))

    # --- coded-block-pattern gating, vectorized ---
    # I_16x16: one bit covering all AC; I_NxN: one bit per 8x8 group
    # (luma4x4BlkIdx 4b..4b+3 form group b under the z-scan).
    cbp_luma = luma_ac.any(axis=(2, 3))                       # (R, C) I16
    i4_grp_any = luma_i4.reshape(nr, nc_mb, 4, 4, 16).any(axis=(3, 4))
    cbp_luma4 = (i4_grp_any * (1 << np.arange(4))).sum(axis=2)  # (R, C)
    chroma_ac_any = cb_ac.any(axis=(2, 3)) | cr_ac.any(axis=(2, 3))
    chroma_dc_any = cb_dc.any(axis=2) | cr_dc.any(axis=2)
    cbp_chroma = np.where(chroma_ac_any, 2,
                          np.where(chroma_dc_any, 1, 0))      # (R, C)

    # --- per-block total_coeff with gating, then nC grids ---
    tc_i16 = np.count_nonzero(luma_ac, axis=3) * cbp_luma[:, :, None]
    grp_bit = (cbp_luma4[:, :, None] >> (np.arange(16) // 4)[None, None]) & 1
    tc_i4 = np.count_nonzero(luma_i4, axis=3) * grp_bit
    tc_luma_blk = np.where(mb_i4[:, :, None], tc_i4, tc_i16)  # (R, C, 16)
    tc_luma = np.zeros((nr, nc_mb, 4, 4), np.int32)           # [by][bx]
    for blk, (bx, by) in enumerate(_BLK_XY):
        tc_luma[:, :, by, bx] = tc_luma_blk[:, :, blk]

    # --- Intra4x4PredMode predictors (8.3.1.1), vectorized ---
    # Raster-layout mode grid with 2 (DC) for non-I4 MBs; A = left block
    # (crossing into the previous MB's bx=3 column), B = above block
    # (available only within the MB under slice-per-row).
    modes_r = np.full((nr, nc_mb, 4, 4), 2, np.int32)
    for blk, (bx, by) in enumerate(_BLK_XY):
        modes_r[:, :, by, bx] = np.where(mb_i4, i4_modes[:, :, blk], 2)
    mode_a = np.full((nr, nc_mb, 4, 4), 2, np.int32)
    a_avail = np.zeros((nr, nc_mb, 4, 4), bool)
    mode_a[:, :, :, 1:] = modes_r[:, :, :, :-1]
    a_avail[:, :, :, 1:] = True
    mode_a[:, 1:, :, 0] = modes_r[:, :-1, :, 3]
    a_avail[:, 1:, :, 0] = True
    mode_b = np.full((nr, nc_mb, 4, 4), 2, np.int32)
    b_avail = np.zeros((nr, nc_mb, 4, 4), bool)
    mode_b[:, :, 1:, :] = modes_r[:, :, :-1, :]
    b_avail[:, :, 1:, :] = True
    pred_i4 = np.where(a_avail & b_avail,
                       np.minimum(mode_a, mode_b), 2)         # (R,C,4,4)

    def chroma_tc(ac):
        t = np.count_nonzero(ac, axis=3) * (cbp_chroma == 2)[:, :, None]
        return t.reshape(nr, nc_mb, 2, 2).astype(np.int32)    # raster [by][bx]

    tc_cb = chroma_tc(cb_ac)
    tc_cr = chroma_tc(cr_ac)

    nc_luma = _nc_grid(tc_luma, tc_luma[:, :, :, 3])
    nc_cb = _nc_grid(tc_cb, tc_cb[:, :, :, 1])
    nc_cr = _nc_grid(tc_cr, tc_cr[:, :, :, 1])
    # Intra16x16DCLevel uses blk (0,0)'s neighbors
    nc_dc = nc_luma[:, :, 0, 0]

    out = bytearray()
    if with_headers:
        out += syn.nal_unit(syn.NAL_SPS, sps)
        out += syn.nal_unit(syn.NAL_PPS, pps)

    if qp_map is not None and slice_qp is None:
        raise ValueError("qp_map requires slice_qp")

    for my in range(nr):
        bw = BitWriter()
        syn.slice_header(bw, first_mb=my * nc_mb, slice_type=7,
                         frame_num=frame_num, idr=True, idr_pic_id=idr_pic_id,
                         qp_delta=qp_delta, deblocking_idc=deblocking_idc)
        prev_qp = slice_qp                           # row-start anchor
        for mx in range(nc_mb):
            cc = int(cbp_chroma[my, mx])
            if mb_i4[my, mx]:
                cl4 = int(cbp_luma4[my, mx])
                syn.write_ue(bw, 0)                  # mb_type: I_NxN
                for blk, (bx, by) in enumerate(_BLK_XY):
                    mode = int(i4_modes[my, mx, blk])
                    pred = int(pred_i4[my, mx, by, bx])
                    if mode == pred:
                        bw.write(1, 1)               # prev_..._flag = 1
                    else:
                        rem = mode - 1 if mode > pred else mode
                        bw.write(rem, 4)             # flag 0 + 3-bit rem
                syn.write_ue(bw, 0)                  # intra_chroma: DC
                syn.write_ue(bw, int(
                    _CBP_INTRA_TO_CODENUM[cl4 + 16 * cc]))
                if cl4 or cc:
                    if qp_map is None:
                        syn.write_se(bw, 0)          # mb_qp_delta
                    else:
                        q = int(qp_map[my, mx])
                        syn.write_se(bw, q - prev_qp)
                        prev_qp = q
                for blk, (bx, by) in enumerate(_BLK_XY):
                    if cl4 & (1 << (blk // 4)):
                        encode_block(bw, luma_i4[my, mx, blk],
                                     int(nc_luma[my, mx, by, bx]), 16)
                if cc > 0:
                    encode_block(bw, cb_dc[my, mx], -1, 4)
                    encode_block(bw, cr_dc[my, mx], -1, 4)
                if cc == 2:
                    for blk in range(4):
                        by, bx = divmod(blk, 2)
                        encode_block(bw, cb_ac[my, mx, blk],
                                     int(nc_cb[my, mx, by, bx]), 15)
                    for blk in range(4):
                        by, bx = divmod(blk, 2)
                        encode_block(bw, cr_ac[my, mx, blk],
                                     int(nc_cr[my, mx, by, bx]), 15)
                continue
            cl = bool(cbp_luma[my, mx])
            # mb_type (Table 7-11): 1 + predMode + 4*cbp_chroma + 12*cbp_luma
            syn.write_ue(bw, 1 + int(pred_mode[my, mx]) + 4 * cc
                         + (12 if cl else 0))
            syn.write_ue(bw, 0)        # intra_chroma_pred_mode: DC
            if qp_map is None:
                syn.write_se(bw, 0)    # mb_qp_delta
            else:                      # I16 always codes the syntax
                q = int(qp_map[my, mx])
                syn.write_se(bw, q - prev_qp)
                prev_qp = q
            encode_block(bw, luma_dc[my, mx], int(nc_dc[my, mx]), 16)
            if cl:
                for blk, (bx, by) in enumerate(_BLK_XY):
                    encode_block(bw, luma_ac[my, mx, blk],
                                 int(nc_luma[my, mx, by, bx]), 15)
            if cc > 0:
                encode_block(bw, cb_dc[my, mx], -1, 4)
                encode_block(bw, cr_dc[my, mx], -1, 4)
            if cc == 2:
                for blk in range(4):
                    by, bx = divmod(blk, 2)
                    encode_block(bw, cb_ac[my, mx, blk],
                                 int(nc_cb[my, mx, by, bx]), 15)
                for blk in range(4):
                    by, bx = divmod(blk, 2)
                    encode_block(bw, cr_ac[my, mx, blk],
                                 int(nc_cr[my, mx, by, bx]), 15)
        syn.rbsp_trailing_bits(bw)
        out += syn.nal_unit(syn.NAL_IDR, bw.getvalue())
    return bytes(out)
