"""MSB-first bit writer with optional JPEG/H.264 byte-stuffing modes.

Pure-Python reference implementation; the C++ twin in ``native/entropy.cpp``
must produce byte-identical output (tested in tests/test_native.py).
"""

from __future__ import annotations


class BitWriter:
    """Accumulates bits MSB-first into a bytearray.

    stuffing:
      - ``None``: raw bits (H.264 RBSP before emulation prevention).
      - ``"jpeg"``: insert a 0x00 after every 0xFF data byte (T.81 §B.1.1.5).
    """

    def __init__(self, stuffing: str | None = None) -> None:
        self.buf = bytearray()
        self._acc = 0          # bit accumulator (int)
        self._nbits = 0        # bits currently in accumulator
        self._stuffing = stuffing

    def write(self, value: int, nbits: int) -> None:
        """Append the low ``nbits`` of ``value``, MSB first."""
        if nbits == 0:
            return
        assert 0 <= value < (1 << nbits), (value, nbits)
        self._acc = (self._acc << nbits) | value
        self._nbits += nbits
        while self._nbits >= 8:
            self._nbits -= 8
            byte = (self._acc >> self._nbits) & 0xFF
            self.buf.append(byte)
            if self._stuffing == "jpeg" and byte == 0xFF:
                self.buf.append(0x00)
        self._acc &= (1 << self._nbits) - 1

    def write_bit(self, bit: int) -> None:
        self.write(bit & 1, 1)

    def pad_to_byte(self, pad_bit: int = 1) -> None:
        """Pad with ``pad_bit`` up to the next byte boundary (JPEG pads 1s)."""
        if self._nbits % 8:
            n = 8 - self._nbits % 8
            self.write(((1 << n) - 1) if pad_bit else 0, n)

    @property
    def bit_position(self) -> int:
        return len(self.buf) * 8 + self._nbits

    def peek_bits(self) -> tuple:
        """(bits, nbits): the whole stream so far as one MSB-first integer,
        including unflushed accumulator bits.  Raw mode only — with byte
        stuffing the integer would contain stuffing bytes."""
        assert self._stuffing is None, "peek_bits is for raw (RBSP) mode"
        return ((int.from_bytes(bytes(self.buf), "big") << self._nbits)
                | self._acc, self.bit_position)

    def getvalue(self) -> bytes:
        assert self._nbits == 0, "unflushed bits; call pad_to_byte() first"
        return bytes(self.buf)
