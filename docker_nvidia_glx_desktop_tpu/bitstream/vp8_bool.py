"""VP8 boolean (arithmetic) coder — RFC 6386 §7.

Encoder state machine follows the normative carry/renormalization
behavior (24-bit staging, carry propagation through emitted bytes);
bit-exactness is proven by (a) the round-trip against the decoder here
and (b) libvpx decoding whole frames produced by this encoder.
"""

from __future__ import annotations

from typing import Sequence

__all__ = ["BoolEncoder", "BoolDecoder", "NORM"]

# leading-zero renormalization shift for range in [1, 255]
NORM = [0] * 256
for _v in range(1, 256):
    _s = 0
    _r = _v
    while _r < 128:
        _r <<= 1
        _s += 1
    NORM[_v] = _s


class BoolEncoder:
    def __init__(self):
        self._low = 0
        self._range = 255
        self._count = -24
        self._buf = bytearray()

    def encode(self, bit: int, prob: int) -> None:
        """Encode one bool; ``prob`` (1..255) is P(bit == 0) scaled 256."""
        split = 1 + (((self._range - 1) * prob) >> 8)
        if bit:
            self._low += split
            rng = self._range - split
        else:
            rng = split
        shift = NORM[rng]
        rng <<= shift
        count = self._count + shift
        low = self._low
        if count >= 0:
            offset = shift - count
            if (low << (offset - 1)) & 0x80000000:
                # carry into already-emitted bytes
                x = len(self._buf) - 1
                while x >= 0 and self._buf[x] == 0xFF:
                    self._buf[x] = 0
                    x -= 1
                if x >= 0:
                    self._buf[x] += 1
            self._buf.append((low >> (24 - offset)) & 0xFF)
            low = (low << offset) & 0xFFFFFF
            shift = count
            count -= 8
        self._low = (low << shift) & 0xFFFFFFFF
        self._range = rng
        self._count = count

    def literal(self, value: int, bits: int) -> None:
        for i in range(bits - 1, -1, -1):
            self.encode((value >> i) & 1, 128)

    def signed_literal(self, value: int, bits: int) -> None:
        """Magnitude then sign (the header's delta-update format)."""
        self.literal(abs(value), bits)
        self.encode(1 if value < 0 else 0, 128)

    def tree(self, tree: Sequence[int], probs: Sequence[int],
             bits: Sequence[int], start: int = 0) -> None:
        """Encode a bit path down a VP8 token tree (probs[i >> 1])."""
        i = start
        for b in bits:
            self.encode(b, probs[i >> 1])
            i = tree[i + b]

    def finish(self) -> bytes:
        for _ in range(32):
            self.encode(0, 128)
        return bytes(self._buf)


class BoolDecoder:
    """RFC 6386 §7.2 decoder (tests / table verification)."""

    def __init__(self, data: bytes):
        self._data = data
        self._pos = 2
        self._value = ((data[0] << 8) | data[1]) if len(data) >= 2 else 0
        self._range = 255
        self._bits = 0

    def _next_byte(self) -> int:
        b = self._data[self._pos] if self._pos < len(self._data) else 0
        self._pos += 1
        return b

    def decode(self, prob: int) -> int:
        split = 1 + (((self._range - 1) * prob) >> 8)
        big = split << 8
        if self._value >= big:
            bit = 1
            self._value -= big
            self._range -= split
        else:
            bit = 0
            self._range = split
        while self._range < 128:
            self._value <<= 1
            self._range <<= 1
            self._bits += 1
            if self._bits == 8:
                self._bits = 0
                self._value |= self._next_byte()
        return bit

    def literal(self, bits: int) -> int:
        v = 0
        for _ in range(bits):
            v = (v << 1) | self.decode(128)
        return v
