"""CABAC normative tables, recovered from system codec binaries.

The spec's arithmetic-coder tables (rangeTabLPS 9-44, transIdxLPS 9-45)
and the 4 context-initialization tables (9-12..9-33: one for I slices,
three cabac_init_idc variants for P/B) are constants in every H.264
implementation.  As with the deblock alpha/beta/tc0 recovery
(ops/h264_deblock.load_tables, the round-3 precedent), they are located
in the system libraries by structural signature and cross-validated:

- libx264 stores the 4 context tables as contiguous ``[1024][2]`` int8
  arrays (I, PB[0], PB[1], PB[2]); libavcodec carries a byte-identical
  copy (two independent codebases agreeing is the validation).
- libx264's ``cabac_transition[128][2]`` packs (state, MPS) as
  ``p = 2*(63 - pStateIdx) + valMPS`` — from it both spec transition
  tables are derived and checked against the spec's structural laws
  (transIdxMPS[s] == min(s+1, 62), mirror symmetry between the two MPS
  rows, LPS of state 0 flips valMPS in place).
- rangeTabLPS is stored in the same reversed-state order directly before
  the transition table's neighborhood; recovered rows are reordered and
  checked (state 0 row == 128,176,208,240, monotone down states).
"""

from __future__ import annotations

import functools

import numpy as np

from ..utils.librecovery import candidate_paths

# Exact paths of the shipped container first, then the shared
# multi-arch glob scan (utils/librecovery).
_LIBS = (
    "/usr/lib/x86_64-linux-gnu/libx264.so.164",
    "/usr/lib/x86_64-linux-gnu/libavcodec.so.59.37.100",
    "/usr/lib/x86_64-linux-gnu/libx264.so",
    "/usr/lib/x86_64-linux-gnu/libavcodec.so",
)


def _candidate_paths():
    return candidate_paths(fixed=_LIBS, stems=("x264", "avcodec"))

_CTX_ANCHOR = bytes([0x14, 0xF1, 0x02, 0x36, 0x03, 0x4A] * 2)  # ctx 0-5
_N_CTX = 1024


def _findall(raw: bytes, pat: bytes):
    out, i = [], -1
    while True:
        i = raw.find(pat, i + 1)
        if i < 0:
            return out
        out.append(i)


def _read_libs():
    blobs = []
    for p in _candidate_paths():
        try:
            blobs.append(open(p, "rb").read())
        except OSError:
            continue
    if not blobs:
        raise RuntimeError(
            "no codec library found for CABAC recovery (need libx264 or "
            "libavcodec installed; see deploy/Dockerfile)")
    return blobs


def _ctx_tables_from(raw: bytes):
    """The four contiguous [1024][2] int8 init tables, or None."""
    hits = _findall(raw, _CTX_ANCHOR)
    runs = [h for h in hits
            if all((h + k * 2 * _N_CTX) in hits for k in range(4))]
    for h in runs:
        block = np.frombuffer(
            raw[h:h + 4 * 2 * _N_CTX], np.int8).reshape(4, _N_CTX, 2)
        # ctx 0-10 are slice-type-independent in the spec — all four
        # tables must agree there
        if all((block[k, :11] == block[0, :11]).all() for k in range(1, 4)):
            return block
    return None


@functools.lru_cache(maxsize=None)
def context_init_tables():
    """(4, 1024, 2) int8: [0] = I slices, [1..3] = cabac_init_idc 0..2
    for P/B slices; cross-validated across every library that has them.

    Identification is structural, not positional: contexts 11-20
    (mb_skip_flag / mb_type for P slices) exist only in the P/B tables,
    so exactly one of the four recovered tables is all-zero there — the
    I table (the binaries store PB0, PB1, PB2, I)."""
    found = None
    for raw in _read_libs():
        t = _ctx_tables_from(raw)
        if t is None:
            continue
        if found is not None and not (found == t).all():
            raise RuntimeError("context-init tables disagree across libs")
        found = t
    if found is None:
        raise RuntimeError("CABAC context-init tables not found")
    i_idx = [k for k in range(4) if not found[k, 11:21].any()]
    if len(i_idx) != 1:
        raise RuntimeError("cannot identify the I-slice init table")
    order = i_idx + [k for k in range(4) if k != i_idx[0]]
    return found[order]


@functools.lru_cache(maxsize=None)
def engine_tables():
    """(range_lps (64, 4) uint8, trans_mps (64,), trans_lps (64,)) in SPEC
    state order, recovered from libx264's packed transition table."""
    for raw in _read_libs():
        # packed transition table: starts (0,0),(1,1) for the two
        # most-confident/terminal packed states and ends ...127,126,125
        for h in _findall(raw, bytes([0, 0, 1, 1, 2, 50, 51, 3])):
            seg = np.frombuffer(raw[h:h + 256], np.uint8).reshape(128, 2)
            if seg[-1, 0] != 126 or seg[-1, 1] != 125:
                continue
            tm = np.zeros(64, np.int32)
            tl = np.zeros(64, np.int32)
            ok = True
            for s in range(64):
                p0 = 2 * (63 - s)
                tm[s] = 63 - (int(seg[p0, 0]) >> 1)
                tl[s] = 63 - (int(seg[p0, 1]) >> 1)
                # valMPS=1 row must mirror the valMPS=0 row
                if (63 - (int(seg[p0 + 1, 1]) >> 1) != tm[s]
                        or 63 - (int(seg[p0 + 1, 0]) >> 1) != tl[s]):
                    ok = False
            ok &= all(int(tm[s]) == min(s + 1, 62) for s in range(63))
            ok &= int(tm[63]) == 63 and int(tl[63]) == 63 and int(tl[0]) == 0
            ok &= (np.diff(tl[:63]) >= 0).all()
            if not ok:
                continue
            # rangeTabLPS: reversed-state [64][4] directly before the
            # transition table in x264's rodata; search nearby, validate
            lo = max(0, h - 4096)
            for r in _findall(raw[lo:h + 4096],
                              bytes([2, 2, 2, 2, 6, 7, 8, 9])):
                rng = np.frombuffer(raw[lo + r:lo + r + 256],
                                    np.uint8).reshape(64, 4)[::-1]
                good = (tuple(rng[0]) == (128, 176, 208, 240)
                        and (np.diff(rng.astype(np.int32), axis=0) <= 0).all()
                        and (np.diff(rng.astype(np.int32), axis=1) >= 0).all())
                if good:
                    return rng.copy(), tm, tl
    raise RuntimeError("CABAC engine tables not found")


def init_contexts(table_idx: int, qp: int):
    """Per-slice context state init (spec 9.3.1.1).

    table_idx: 0 = I slice; 1+cabac_init_idc for P slices.
    Returns (pStateIdx (1024,) uint8, valMPS (1024,) uint8).
    """
    mn = context_init_tables()[table_idx].astype(np.int32)
    m, n = mn[:, 0], mn[:, 1]
    pre = np.clip(((m * np.clip(qp, 0, 51)) >> 4) + n, 1, 126)
    mps = pre > 63
    state = np.where(mps, pre - 64, 63 - pre)
    return state.astype(np.uint8), mps.astype(np.uint8)
