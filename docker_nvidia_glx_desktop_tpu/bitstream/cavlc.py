"""CAVLC residual coding (ITU-T H.264 §9.2) — pure-Python reference.

This is the entropy half of the ``nvh264enc`` replacement (reference
Dockerfile:210): NVENC's silicon CAVLC stage re-implemented first-party.
The native C++ fast path (``native/cavlc.cpp``) must produce byte-identical
output; tests enforce that.  Tables below are transcribed from the spec
(Tables 9-5, 9-7, 9-8, 9-9(a), 9-10); `_check_prefix_free` validates each
is a well-formed prefix code at import time so a transcription slip fails
loudly rather than emitting broken streams.
"""

from __future__ import annotations

from .bitwriter import BitWriter

# ---------------------------------------------------------------------------
# Table 9-5: coeff_token.  Layout: [nC-class][4*TotalCoeff + TrailingOnes]
# -> (length, bits).  Classes: 0: 0<=nC<2, 1: 2<=nC<4, 2: 4<=nC<8,
# 3: nC>=8 (6-bit FLC, generated), 4: nC==-1 (chroma DC).
# ---------------------------------------------------------------------------

_CT_LEN = [
    # 0 <= nC < 2
    [1, 0, 0, 0,
     6, 2, 0, 0,
     8, 6, 3, 0,
     9, 8, 7, 5,
     10, 9, 8, 6,
     11, 10, 9, 7,
     13, 11, 10, 8,
     13, 13, 11, 9,
     13, 13, 13, 10,
     14, 14, 13, 11,
     14, 14, 14, 13,
     15, 15, 14, 14,
     15, 15, 15, 14,
     16, 15, 15, 15,
     16, 16, 16, 15,
     16, 16, 16, 16,
     16, 16, 16, 16],
    # 2 <= nC < 4
    [2, 0, 0, 0,
     6, 2, 0, 0,
     6, 5, 3, 0,
     7, 6, 6, 4,
     8, 6, 6, 4,
     8, 7, 7, 5,
     9, 8, 8, 6,
     11, 9, 9, 6,
     11, 11, 11, 7,
     12, 11, 11, 9,
     12, 12, 12, 11,
     12, 12, 12, 11,
     13, 13, 13, 12,
     13, 13, 13, 13,
     13, 14, 13, 13,
     14, 14, 14, 13,
     14, 14, 14, 14],
    # 4 <= nC < 8
    [4, 0, 0, 0,
     6, 4, 0, 0,
     6, 5, 4, 0,
     6, 5, 5, 4,
     7, 5, 5, 4,
     7, 5, 5, 4,
     7, 6, 6, 4,
     7, 6, 6, 4,
     8, 7, 7, 5,
     8, 8, 7, 6,
     9, 8, 8, 7,
     9, 9, 8, 8,
     9, 9, 9, 8,
     10, 9, 9, 9,
     10, 10, 10, 10,
     10, 10, 10, 10,
     10, 10, 10, 10],
]

_CT_BITS = [
    [1, 0, 0, 0,
     5, 1, 0, 0,
     7, 4, 1, 0,
     7, 6, 5, 3,
     7, 6, 5, 3,
     7, 6, 5, 4,
     15, 6, 5, 4,
     11, 14, 5, 4,
     8, 10, 13, 4,
     15, 14, 9, 4,
     11, 10, 13, 12,
     15, 14, 9, 12,
     11, 10, 13, 8,
     15, 1, 9, 12,
     11, 14, 13, 8,
     7, 10, 9, 12,
     4, 6, 5, 8],
    [3, 0, 0, 0,
     11, 2, 0, 0,
     7, 7, 3, 0,
     7, 10, 9, 5,
     7, 6, 5, 4,
     4, 6, 5, 6,
     7, 6, 5, 8,
     15, 6, 5, 4,
     11, 14, 13, 4,
     15, 10, 9, 4,
     11, 14, 13, 12,
     8, 10, 9, 8,
     15, 14, 13, 12,
     11, 10, 9, 12,
     7, 11, 6, 8,
     9, 8, 10, 1,
     7, 6, 5, 4],
    [15, 0, 0, 0,
     15, 14, 0, 0,
     11, 15, 13, 0,
     8, 12, 14, 12,
     15, 10, 11, 11,
     11, 8, 9, 10,
     9, 14, 13, 9,
     8, 10, 9, 8,
     15, 14, 13, 13,
     11, 14, 10, 12,
     15, 10, 13, 12,
     11, 14, 9, 12,
     8, 10, 13, 8,
     13, 7, 9, 12,
     9, 12, 11, 10,
     5, 8, 7, 6,
     1, 4, 3, 2],
]

# nC == -1 (chroma DC 2x2, Table 9-5 rightmost column)
_CT_LEN_CDC = [2, 0, 0, 0,
               6, 1, 0, 0,
               6, 6, 3, 0,
               6, 7, 7, 6,
               6, 8, 8, 7]
_CT_BITS_CDC = [1, 0, 0, 0,
                7, 1, 0, 0,
                4, 6, 1, 0,
                3, 3, 2, 5,
                2, 3, 2, 0]


def _ct_flc(tc: int, t1: int) -> tuple[int, int]:
    """nC >= 8: 6-bit fixed-length coeff_token."""
    if tc == 0:
        return 6, 3
    return 6, ((tc - 1) << 2) | t1


# ---------------------------------------------------------------------------
# Tables 9-7/9-8: total_zeros for 4x4 blocks, indexed [TotalCoeff-1][tz]
# ---------------------------------------------------------------------------

_TZ_LEN = [
    [1, 3, 3, 4, 4, 5, 5, 6, 6, 7, 7, 8, 8, 9, 9, 9],
    [3, 3, 3, 3, 3, 4, 4, 4, 4, 5, 5, 6, 6, 6, 6],
    [4, 3, 3, 3, 4, 4, 3, 3, 4, 5, 5, 6, 5, 6],
    [5, 3, 4, 4, 3, 3, 3, 4, 3, 4, 5, 5, 5],
    [4, 4, 4, 3, 3, 3, 3, 3, 4, 5, 4, 5],
    [6, 5, 3, 3, 3, 3, 3, 3, 4, 3, 6],
    [6, 5, 3, 3, 3, 2, 3, 4, 3, 6],
    [6, 4, 5, 3, 2, 2, 3, 3, 6],
    [6, 6, 4, 2, 2, 3, 2, 5],
    [5, 5, 3, 2, 2, 2, 4],
    [4, 4, 3, 3, 1, 3],
    [4, 4, 2, 1, 3],
    [3, 3, 1, 2],
    [2, 2, 1],
    [1, 1],
]

_TZ_BITS = [
    [1, 3, 2, 3, 2, 3, 2, 3, 2, 3, 2, 3, 2, 3, 2, 1],
    [7, 6, 5, 4, 3, 5, 4, 3, 2, 3, 2, 3, 2, 1, 0],
    [5, 7, 6, 5, 4, 3, 4, 3, 2, 3, 2, 1, 1, 0],
    [3, 7, 5, 4, 6, 5, 4, 3, 3, 2, 2, 1, 0],
    [5, 4, 3, 7, 6, 5, 4, 3, 2, 1, 1, 0],
    [1, 1, 7, 6, 5, 4, 3, 2, 1, 1, 0],
    [1, 1, 5, 4, 3, 3, 2, 1, 1, 0],
    [1, 1, 1, 3, 3, 2, 2, 1, 0],
    [1, 0, 1, 3, 2, 1, 1, 1],
    [1, 0, 1, 3, 2, 1, 1],
    [0, 1, 1, 2, 1, 3],
    [0, 1, 1, 1, 1],
    [0, 1, 1, 1],
    [0, 1, 1],
    [0, 1],
]

# Table 9-9(a): total_zeros for chroma DC (maxNumCoeff 4), [TC-1][tz]
_TZ_LEN_CDC = [[1, 2, 3, 3], [1, 2, 2], [1, 1]]
_TZ_BITS_CDC = [[1, 1, 1, 0], [1, 1, 0], [1, 0]]

# Table 9-10: run_before, indexed [min(zerosLeft,7)-1][run]
_RB_LEN = [
    [1, 1],
    [1, 2, 2],
    [2, 2, 2, 2],
    [2, 2, 2, 3, 3],
    [2, 2, 3, 3, 3, 3],
    [2, 3, 3, 3, 3, 3, 3],
    [3, 3, 3, 3, 3, 3, 3, 4, 5, 6, 7, 8, 9, 10, 11],
]
_RB_BITS = [
    [1, 0],
    [1, 1, 0],
    [3, 2, 1, 0],
    [3, 2, 1, 1, 0],
    [3, 2, 3, 2, 1, 0],
    [3, 0, 1, 3, 2, 5, 4],
    [7, 6, 5, 4, 3, 2, 1, 1, 1, 1, 1, 1, 1, 1, 1],
]


def _check_prefix_free() -> None:
    """Import-time sanity: every table is a prefix-free code."""
    def check(pairs, what):
        codes = [(ln, bits) for ln, bits in pairs if ln > 0]
        seen = set()
        for ln, bits in codes:
            assert bits < (1 << ln), (what, ln, bits)
            seen.add((ln, bits))
        assert len(seen) == len(codes), f"{what}: duplicate codes"
        for ln_a, b_a in codes:
            for ln_b, b_b in codes:
                if ln_a < ln_b and (b_b >> (ln_b - ln_a)) == b_a:
                    raise AssertionError(f"{what}: prefix violation")

    for cls in range(3):
        pairs = []
        for tc in range(17):
            for t1 in range(min(tc, 3) + 1):
                pairs.append((_CT_LEN[cls][4 * tc + t1],
                              _CT_BITS[cls][4 * tc + t1]))
        check(pairs, f"coeff_token[{cls}]")
    pairs = [(_CT_LEN_CDC[4 * tc + t1], _CT_BITS_CDC[4 * tc + t1])
             for tc in range(5) for t1 in range(min(tc, 3) + 1)]
    check(pairs, "coeff_token[chromaDC]")
    for i, (lens, bits) in enumerate(zip(_TZ_LEN, _TZ_BITS)):
        check(list(zip(lens, bits)), f"total_zeros[{i}]")
    for i, (lens, bits) in enumerate(zip(_TZ_LEN_CDC, _TZ_BITS_CDC)):
        check(list(zip(lens, bits)), f"total_zeros_cdc[{i}]")
    for i, (lens, bits) in enumerate(zip(_RB_LEN, _RB_BITS)):
        check(list(zip(lens, bits)), f"run_before[{i}]")


_check_prefix_free()


# ---------------------------------------------------------------------------
# Block encoder
# ---------------------------------------------------------------------------

def encode_block(bw: BitWriter, levels, nc: int, max_coeff: int) -> int:
    """CAVLC-code one residual block (levels in scan order, length
    ``max_coeff``).  ``nc``: context from neighbor totals, or -1 for chroma
    DC.  Returns TotalCoeff (the caller records it for neighbor nC).
    """
    nz = [(i, int(v)) for i, v in enumerate(levels) if v]
    total = len(nz)
    # trailing ones: up to 3 final +-1s in scan order
    t1 = 0
    while t1 < 3 and t1 < total and abs(nz[total - 1 - t1][1]) == 1:
        t1 += 1

    if nc == -1:
        ln, bits = _CT_LEN_CDC[4 * total + t1], _CT_BITS_CDC[4 * total + t1]
    elif nc >= 8:
        ln, bits = _ct_flc(total, t1)
    else:
        cls = 0 if nc < 2 else (1 if nc < 4 else 2)
        ln, bits = _CT_LEN[cls][4 * total + t1], _CT_BITS[cls][4 * total + t1]
    assert ln > 0, (total, t1, nc)
    bw.write(bits, ln)
    if total == 0:
        return 0

    # trailing-one signs, highest frequency first
    for k in range(t1):
        bw.write(1 if nz[total - 1 - k][1] < 0 else 0, 1)

    # remaining levels, highest frequency first
    suffix_len = 1 if total > 10 and t1 < 3 else 0
    first = True
    for k in range(total - 1 - t1, -1, -1):
        level = nz[k][1]
        code = 2 * level - 2 if level > 0 else -2 * level - 1
        if first and t1 < 3:
            code -= 2      # first non-T1 level cannot be +-1
        first = False
        _write_level(bw, code, suffix_len)
        if suffix_len == 0:
            suffix_len = 1
        if abs(level) > (3 << (suffix_len - 1)) and suffix_len < 6:
            suffix_len += 1

    # total_zeros
    tz = nz[total - 1][0] + 1 - total
    if total < max_coeff:
        if nc == -1:
            bw.write(_TZ_BITS_CDC[total - 1][tz], _TZ_LEN_CDC[total - 1][tz])
        else:
            bw.write(_TZ_BITS[total - 1][tz], _TZ_LEN[total - 1][tz])

    # run_before, highest frequency first; last coded coeff's run implied
    zeros_left = tz
    for k in range(total - 1, 0, -1):
        if zeros_left <= 0:
            break
        run = nz[k][0] - nz[k - 1][0] - 1
        row = _RB_LEN[min(zeros_left, 7) - 1]
        bw.write(_RB_BITS[min(zeros_left, 7) - 1][run], row[run])
        zeros_left -= run
    return total


def _write_level(bw: BitWriter, code: int, suffix_len: int) -> None:
    """level_prefix / level_suffix per §9.2.2.1, including the
    level_prefix >= 16 escape extension for arbitrarily large levels."""
    if suffix_len == 0:
        if code < 14:
            bw.write(1, code + 1)            # code zeros then a 1
            return
        if code < 30:
            bw.write(1, 15)                  # prefix 14, 4-bit suffix
            bw.write(code - 14, 4)
            return
        extra = 15                           # levelCode += 15 when sl == 0
    else:
        prefix = code >> suffix_len
        if prefix < 15:
            bw.write(1, prefix + 1)
            bw.write(code & ((1 << suffix_len) - 1), suffix_len)
            return
        extra = 0
    if code < (15 << suffix_len) + extra + 4096:
        bw.write(1, 16)                      # prefix 15, 12-bit suffix
        bw.write(code - (15 << suffix_len) - extra, 12)
        return
    p = 16                                   # prefix >= 16: suffix p-3 bits,
    while True:                              # levelCode += (1<<(p-3)) - 4096
        base = (15 << suffix_len) + extra + (1 << (p - 3)) - 4096
        if code < base + (1 << (p - 3)):
            bw.write(1, p + 1)
            bw.write(code - base, p - 3)
            return
        p += 1


def nc_from_neighbors(na: int | None, nb: int | None) -> int:
    """§9.2.1: context from left (na) / above (nb) block coefficient counts;
    None = neighbor unavailable."""
    if na is not None and nb is not None:
        return (na + nb + 1) >> 1
    if na is not None:
        return na
    if nb is not None:
        return nb
    return 0
