"""Cross-thread attribute-ownership check (the encode-thread <->
event-loop boundary).

The serving classes run a dedicated encode thread next to the asyncio
control plane and communicate through exactly three sanctioned
mechanisms: ``loop.call_soon_threadsafe`` marshals, single-writer
scalar flags (GIL-atomic reference swaps, documented per attribute),
and explicit locks (``_resize_lock``).  PR 6's
``request_degrade_level``/``_rebuild_mesh`` plumbing exists precisely
because an attribute mutated from a websocket handler and read by the
encode thread mid-tick is a silent race.

This pass makes the convention mechanical.  ``OWNERSHIP`` below is the
annotation registry: for each class it names the thread entry points
and every attribute that is *allowed* to be touched from both sides,
with the reason it is safe.  The analyzer recomputes the two sides from
the AST (closure of ``self.x()`` calls from the thread entries;
closure from the public/async surface for the loop side;
``call_soon_threadsafe(self.m, ...)`` targets count as loop-side) and
reports:

- ``thread-shared-attr`` — an attribute written on one side and
  touched on the other that is NOT in the registry: route it through
  the queue/marshal, guard it with the session lock, or — if it is a
  genuinely benign single-writer flag — register it here with the
  reason, which is the code review.
- ``thread-ownership-stale`` — a registry entry the code no longer
  shares: delete it so the registry stays the honest, minimal map of
  the boundary.

``__init__`` accesses are ignored (they happen before the thread
starts).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Set

from .engine import Finding, SourceFile, register_pass

__all__ = ["OWNERSHIP", "run"]


@dataclasses.dataclass
class ClassOwnership:
    thread_entry: tuple           # methods the dedicated thread runs
    shared_ok: Dict[str, str]     # attr -> why cross-thread use is safe
    # public-named methods whose CONTRACT is encode-thread-only (their
    # docstring says so); without this the analyzer would treat every
    # public method as loop-callable surface
    not_loop: tuple = ()


# -- the annotation registry ---------------------------------------------
# Keyed by package-relative path, then class name.  Every entry's reason
# is load-bearing documentation: if you cannot write the reason, the
# attribute is not safe to share.
OWNERSHIP: Dict[str, Dict[str, ClassOwnership]] = {
    "docker_nvidia_glx_desktop_tpu/web/session.py": {
        "StreamSession": ClassOwnership(
            thread_entry=("_run",),
            shared_ok={
                "_stop": "threading.Event (internally locked)",
                "_need_frame": "single-writer-per-side bool; worst case "
                               "one extra/missed poll, re-requested next "
                               "frame",
                "_fps_cap": "single loop-side writer, atomic ref swap; "
                            "thread re-reads every iteration",
                "_qp_offset": "single loop-side writer (degrade "
                              "executor), atomic int swap",
                "_pending_resize": "guarded by _resize_lock on both "
                                   "sides",
                "_pending_adopt": "guarded by _adopt_lock on both "
                                  "sides (adopt_handoff queues on the "
                                  "loop, _consume_adopt pops on the "
                                  "encode thread between frames — the "
                                  "_pending_resize pattern)",
                "encoder": "rebuilt by the thread during recovery; loop "
                           "only calls request_keyframe (idempotent flag "
                           "set on the encoder) and export_handoff, "
                           "whose contract requires the thread stopped",
                "_prewarm": "(thread, stop_event) pair swapped whole; "
                            "writers are start/stop (loop) and "
                            "_recover_device (thread) which never "
                            "overlap — recovery runs inside the live "
                            "thread the loop-side writers join first",
                "_healthz_grace_until": "monotonic float, single writer "
                                        "at a time; healthz reads a "
                                        "possibly stale grace window "
                                        "(benign)",
                "_au_listeners": "list appended on the loop; thread "
                                 "iterates over a list() copy",
                "_recoveries": "thread-written int, stats read "
                               "(one-frame staleness is fine)",
                "_submit_ms": "bounded deque: thread appends, stats "
                              "reads a sorted() copy — deque ops are "
                              "GIL-atomic",
                "_collect_ms": "bounded deque: thread appends, stats "
                               "reads a sorted() copy — deque ops are "
                               "GIL-atomic",
                "muxer": "rebuilt only on the encode thread "
                         "(_setup_codec via resize/recovery); loop "
                         "reads mime for hello (stale for at most one "
                         "resize announce, re-helloed after)",
                "init_segment": "same lifecycle as muxer; subscribe "
                                "snapshots it into the first queue item",
                "codec_name": "same lifecycle as muxer",
                "_idr_last_grant": "guarded by _idr_lock on both sides "
                                   "(request_idr from loop/thread, "
                                   "_idr_tick on the encode thread)",
                "_idr_deferred": "guarded by _idr_lock on both sides "
                                 "(same request_idr/_idr_tick pair)",
            }),
    },
    # The SCTP/DataChannel subsystem (ISSUE 11) is EVENT-LOOP-OWNED by
    # contract: every entry point (receive/send/poll_timeout, DCEP
    # dispatch) runs on the loop — fed by ice.datagram_received and the
    # peer's asyncio timer task — and cross-thread producers must
    # marshal via call_soon_threadsafe.  Empty thread_entry encodes
    # exactly that: the analyzer verifies no method ever lands on the
    # encode-thread side, so any future thread entry point added to
    # these classes must come back here and declare its shared surface.
    "docker_nvidia_glx_desktop_tpu/webrtc/sctp.py": {
        "SctpAssociation": ClassOwnership(
            thread_entry=(),
            shared_ok={}),
    },
    "docker_nvidia_glx_desktop_tpu/webrtc/datachannel.py": {
        "DataChannelEndpoint": ClassOwnership(
            thread_entry=(),
            shared_ok={}),
        "DataChannel": ClassOwnership(
            thread_entry=(),
            shared_ok={}),
    },
    # The ingress governor (ISSUE 18) is EVENT-LOOP-OWNED like the
    # parsers it gates: every charge/violation happens inside a decode
    # callback on the session loop, and the only cross-thread state is
    # the module-level peer gauge, which takes its own lock.  Empty
    # thread_entry = the analyzer proves no method lands on the
    # encode-thread side.
    "docker_nvidia_glx_desktop_tpu/resilience/ingress.py": {
        "PeerBudget": ClassOwnership(
            thread_entry=(),
            shared_ok={}),
        "ProbeWindow": ClassOwnership(
            thread_entry=(),
            shared_ok={}),
        "TokenBucket": ClassOwnership(
            thread_entry=(),
            shared_ok={}),
    },
    # The RTCP feedback plane (ISSUE 14) shares the SCTP contract:
    # EVENT-LOOP-OWNED.  AU delivery is marshalled onto the loop by the
    # peer before the plane/pacer/history run, RTCP ingestion arrives
    # on the loop via ice.datagram_received, and the pacer's drain task
    # is a loop task.  Empty thread_entry = the analyzer proves no
    # method lands on the encode-thread side; a future thread entry
    # must come back here and declare its shared surface.
    "docker_nvidia_glx_desktop_tpu/webrtc/feedback.py": {
        "PacketHistory": ClassOwnership(
            thread_entry=(),
            shared_ok={}),
        "Pacer": ClassOwnership(
            thread_entry=(),
            shared_ok={}),
        "FeedbackPlane": ClassOwnership(
            thread_entry=(),
            shared_ok={}),
        "FrameSeqLog": ClassOwnership(
            thread_entry=(),
            shared_ok={}),
        "FeedbackSink": ClassOwnership(
            thread_entry=(),
            shared_ok={}),
    },
    # The frame-journey / event-timeline / flight-recorder classes
    # (ISSUE 13) are BOTH-SIDES by design: the encode thread mints and
    # completes journeys and emits events (fault sites run wherever the
    # fault fires), the event loop closes journeys (client acks, RTCP)
    # and serves the /debug endpoints.  Every shared container below is
    # mutated only under the instance's own _lock; registering them
    # here is the machine-checked statement of that contract.
    "docker_nvidia_glx_desktop_tpu/obs/journey.py": {
        "JourneyBook": ClassOwnership(
            thread_entry=("mint", "complete"),
            shared_ok={
                "_j": "journey dict; every mutation under _lock",
                "_order": "ring deque; every mutation under _lock",
                "_by_pts": "pts index; every mutation under _lock",
                "_frontier": "int updated under _lock; readers see a "
                             "possibly one-frame-stale frontier "
                             "(benign for event anchoring)",
                "_chunk_device": "chunk device-ms map; every mutation "
                                 "under _lock",
            }),
    },
    "docker_nvidia_glx_desktop_tpu/obs/events.py": {
        "EventLog": ClassOwnership(
            thread_entry=("emit",),
            shared_ok={
                "_ring": "bounded deque: emit appends under _lock; "
                         "readers snapshot a list() copy under _lock",
                "_listeners": "list appended on the loop at wiring "
                              "time; emit iterates a list() copy",
            }),
    },
    "docker_nvidia_glx_desktop_tpu/obs/flight.py": {
        "FlightRecorder": ClassOwnership(
            thread_entry=("on_event",),
            shared_ok={
                "_dumps": "ring deque; every mutation under _lock",
                "_counts": "cumulative counts; mutations under _lock",
                "_last": "debounce map; mutations under _lock",
                "_seq": "int incremented under _lock",
                "_providers": "dict written at wiring time (loop), "
                              "dump iterates a list() copy",
                "_spool_q": "queue.Queue (internally locked); the "
                            "lazy (re)spawn check-and-swap runs under "
                            "_lock on every path",
                "_spool_thread": "same lazy-spawn lifecycle as "
                                 "_spool_q (under _lock); flush_spool "
                                 "only reads",
            }),
    },
    "docker_nvidia_glx_desktop_tpu/obs/content.py": {
        # Content & quality plane (ISSUE 17): record() runs on each
        # session's encode thread; /debug/content, scrape-time gauge
        # reads and the flight provider run on the event loop.
        "ContentPlane": ClassOwnership(
            thread_entry=("record",),
            shared_ok={
                "_s": "per-session state dicts; every structural "
                      "mutation and every deque append under _lock; "
                      "readers snapshot list() copies under _lock",
            }),
    },
    # The handoff broker (ISSUE 19) is EVENT-LOOP-OWNED except for the
    # drain path: handoff_migrate runs export/spool in the default
    # executor (run_in_executor) so the loop keeps serving in-flight
    # sockets while the encode threads park — those two methods are the
    # declared thread side.  They run only AFTER drain.begin() stopped
    # new /ws joins, so the loop-side writers still alive during an
    # export are detach (dict pop, GIL-atomic) and the status read.
    "docker_nvidia_glx_desktop_tpu/resilience/handoff.py": {
        "HandoffManager": ClassOwnership(
            thread_entry=("export", "spool"),
            shared_ok={
                "_live": "export iterates a list() copy; the only "
                         "loop-side mutation possible during a drain "
                         "is detach's dict pop (GIL-atomic) — entries "
                         "are never mutated in place",
                "exports": "executor-written int, status read "
                           "(one-export staleness is fine)",
                "failures": "int incremented on either side "
                            "(GIL-atomic); telemetry-only, the status "
                            "block may read one bump stale",
            }),
    },
    "docker_nvidia_glx_desktop_tpu/web/multisession.py": {
        "BatchStreamManager": ClassOwnership(
            thread_entry=("_run",),
            # contract stated in its docstring: "Runs on the encode
            # thread between ticks" (the fault-injection path in _run)
            not_loop=("mark_chip_dead",),
            shared_ok={
                "_stop": "threading.Event (internally locked)",
                "_force_idr": "single-writer-per-side bool; worst case "
                              "one duplicate IDR tick",
                "_pending_degrade": "the documented queue: loop writes "
                                    "the level, encode thread consumes "
                                    "it between ticks "
                                    "(request_degrade_level contract)",
                "_degrade_level": "thread-written after a re-bucket; "
                                  "loop reads for capacity modeling "
                                  "(one-tick staleness is the modeled "
                                  "norm)",
                "_dead_devices": "appended on the encode thread; loop "
                                 "reads len() via surviving_chips "
                                 "(one-tick staleness feeds a capacity "
                                 "model that is itself smoothed)",
                "_rebuilds": "thread-written int, stats read",
                "mesh": "rebuilt on the encode thread between ticks; "
                        "stats read shape only",
                "_probe": "swapped on the encode thread during "
                          "re-bucket; loop reads geometry for stats/"
                          "ledger (re-announced after swap)",
            }),
    },
}


def _method_map(cls: ast.ClassDef) -> Dict[str, ast.AST]:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _self_calls(fn) -> Set[str]:
    """Names of ``self.x(...)`` calls inside ``fn`` (nested defs
    included — they run on the same side unless marshalled)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute):
            v = node.func.value
            if isinstance(v, ast.Name) and v.id == "self":
                out.add(node.func.attr)
    return out


def _marshal_targets(fn) -> Set[str]:
    """Methods handed to ``call_soon_threadsafe`` — they run on the
    LOOP regardless of which side schedules them."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) and \
                node.func.attr == "call_soon_threadsafe" and node.args:
            tgt = node.args[0]
            if isinstance(tgt, ast.Attribute) and isinstance(
                    tgt.value, ast.Name) and tgt.value.id == "self":
                out.add(tgt.attr)
    return out


def _closure(methods: Dict[str, ast.AST], roots: Set[str],
             stop: Set[str]) -> Set[str]:
    seen: Set[str] = set()
    todo = [r for r in roots if r in methods]
    while todo:
        m = todo.pop()
        if m in seen or m in stop:
            continue
        seen.add(m)
        for callee in _self_calls(methods[m]):
            if callee in methods and callee not in seen:
                todo.append(callee)
    return seen


# container-mutator method names: self.x.append(...) mutates x even
# though the attribute itself is never rebound
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear",
             "add", "discard", "update", "setdefault", "popitem"}


def _attr_accesses(fn):
    """(reads, writes) of ``self.x`` inside ``fn``.  Rebinds, augmented
    assigns, subscript stores (``self.x[i] = ...``) and container-
    mutator calls (``self.x.append(...)``) all count as writes."""
    reads: Set[str] = set()
    writes: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name) and node.value.id == "self":
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                writes.add(node.attr)
            else:
                reads.add(node.attr)
        elif isinstance(node, ast.AugAssign) and isinstance(
                node.target, ast.Attribute) and isinstance(
                node.target.value, ast.Name) and \
                node.target.value.id == "self":
            writes.add(node.target.attr)
        elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, (ast.Store, ast.Del)) and isinstance(
                node.value, ast.Attribute) and isinstance(
                node.value.value, ast.Name) and \
                node.value.value.id == "self":
            writes.add(node.value.attr)
        elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS and isinstance(
                node.func.value, ast.Attribute) and isinstance(
                node.func.value.value, ast.Name) and \
                node.func.value.value.id == "self":
            writes.add(node.func.value.attr)
    return reads, writes


def _first_site(cls: ast.ClassDef, methods: Set[str],
                attr: str, want_write: bool):
    """The first AST node in ``methods`` that accesses ``attr``."""
    mm = _method_map(cls)
    for name in sorted(methods):
        fn = mm.get(name)
        if fn is None:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) and isinstance(
                    node.value, ast.Name) and node.value.id == "self" \
                    and node.attr == attr:
                if not want_write or isinstance(
                        node.ctx, (ast.Store, ast.Del)):
                    return node, name
    return None, None


def run(src: SourceFile) -> Iterable[Finding]:
    spec_by_class = OWNERSHIP.get(src.rel)
    if not spec_by_class:
        return []
    out: List[Finding] = []
    for node in src.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        spec = spec_by_class.get(node.name)
        if spec is None:
            continue
        methods = _method_map(node)
        marshals: Set[str] = set()
        for fn in methods.values():
            marshals |= _marshal_targets(fn)
        thread_set = _closure(methods, set(spec.thread_entry), marshals)
        loop_roots = {m for m in methods
                      if not m.startswith("_") or m in marshals
                      or isinstance(methods[m], ast.AsyncFunctionDef)}
        loop_roots -= set(spec.thread_entry)
        loop_roots -= set(spec.not_loop)
        loop_set = _closure(methods, loop_roots, set())
        loop_set.discard("__init__")
        thread_set.discard("__init__")

        def side_accesses(side: Set[str]):
            reads: Set[str] = set()
            writes: Set[str] = set()
            for m in side:
                fn = methods.get(m)
                if fn is None:
                    continue
                r, w = _attr_accesses(fn)
                reads |= r
                writes |= w
            return reads, writes

        t_reads, t_writes = side_accesses(thread_set)
        l_reads, l_writes = side_accesses(loop_set)
        shared = ((t_writes & (l_reads | l_writes))
                  | (l_writes & (t_reads | t_writes)))
        for attr in sorted(shared):
            if attr in spec.shared_ok:
                continue
            want_write = attr in t_writes
            site, meth = _first_site(node, thread_set if want_write
                                     else loop_set, attr, True)
            if site is None:
                site, meth = node, node.name
            fi = src.finding(
                "thread-shared-attr", site, f"{node.name}.{meth}",
                f"attribute self.{attr} is written on one side of the "
                "encode-thread/event-loop boundary and touched on the "
                "other without a registered safety contract — marshal "
                "it (call_soon_threadsafe / the pending-* queue "
                "pattern), lock it, or register it in "
                "analysis/ownership.py with the reason it is safe")
            if fi:
                out.append(fi)
        for attr in sorted(set(spec.shared_ok) - shared):
            fi = src.finding(
                "thread-ownership-stale", node, node.name,
                f"registry entry {node.name}.{attr} is no longer "
                "shared across the thread boundary — delete it from "
                "analysis/ownership.py so the registry stays minimal")
            if fi:
                out.append(fi)
    return out


# webrtc joined the scope with the SCTP/DataChannel subsystem (ISSUE
# 11): the ownership pass is registry-driven, so only the classes
# declared above are analyzed there.
register_pass("ownership-pass", ("web", "fleet", "resilience", "webrtc",
                                 "obs"),
              run)
