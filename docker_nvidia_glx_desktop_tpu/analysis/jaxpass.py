"""jax-pass: retrace / host-sync lints over the device program.

Scope: ``ops/``, ``models/``, ``parallel/`` — everywhere a traced value
can leak to the host or a trace can silently re-specialize.  Rules:

- ``jax-host-sync`` — ``float()/int()/bool()``, ``.item()/.tolist()``
  or ``np.asarray/np.array`` applied to a *traced* value inside a
  jit-compiled function (or a ``lax.scan``/``fori_loop``/``while_loop``
  body).  Inside a trace these either abort with a tracer error or —
  the silent case this lint exists for — concretize at trace time and
  bake a stale constant into the executable.  In eager hot paths the
  same call is a synchronous device round-trip per frame.
- ``jax-host-roundtrip`` — a value pulled to the host with
  ``np.asarray`` and then re-uploaded (``jnp.asarray``/``jnp.array``/
  ``device_put``) in the same hot-path function: two wire crossings
  (a full RTT each on a tunnel-attached chip) for work the device
  could do in place.
- ``jax-donate-missing`` — a jitted function takes ring-buffer-style
  arguments (``ref_*``/``prev_*``/``carry``/``ring*``) but declares no
  ``donate_argnums``/``donate_argnames``: every step copies the ring
  instead of aliasing it (ROADMAP item 2's donated-buffer step).
- ``jax-nonhashable-static`` — a ``static_argnames`` entry whose
  parameter default is unhashable (list/dict/set): every call raises
  once that default is exercised.
- ``jax-unmarked-static`` — a ``str``/``bool``-annotated parameter of a
  jitted function that is not marked static: strings fail at trace
  time; bools trace into the graph and turn Python branching into a
  TracerBoolConversionError (or a retrace per value when hashed).
- ``jax-float64`` — explicit float64 (``astype``/``dtype=``/
  ``np.float64()``) inside a jitted function: under the default x64
  switch this silently becomes float32; with x64 enabled it doubles
  device memory traffic.  Either way the kernel author meant one of
  them, so say which (dngd pragma the deliberate case).
- ``jax-mutable-global-capture`` — a module-level ``list``/``dict``/
  ``set`` read inside a jitted function: the trace captures a snapshot,
  later mutations never re-trigger tracing, and the executable serves
  stale data forever.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from .engine import JAX_SCOPE, Finding, SourceFile, register_pass

__all__ = ["run"]

# method/function name prefixes that constitute the per-frame hot path
# for the eager-context round-trip rule (models orchestration code)
HOT_PATH_PREFIXES = ("encode", "_encode", "_submit", "_collect", "_pull",
                     "_gop_step", "_planes", "step", "_step")

_SYNC_BUILTINS = {"float", "int", "bool", "complex"}
_SYNC_METHODS = {"item", "tolist"}
_UNTAINT_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes"}
_RING_NAMES = {"carry", "ring"}
_RING_PREFIXES = ("ref_", "prev_", "ring_")
_LAX_BODY_FNS = {"scan", "fori_loop", "while_loop", "cond", "switch",
                 "associative_scan"}


def _dotted(node: ast.AST) -> str:
    """'jnp.asarray' for Attribute chains, 'float' for Names, else ''."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class JitSpec:
    """What a jit wrapper declares about a function."""

    def __init__(self):
        self.is_jit = False
        self.static_names: Set[str] = set()
        self.static_nums: Set[int] = set()
        self.donates = False

    def absorb_call_kwargs(self, call: ast.Call) -> None:
        for kw in call.keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                self.donates = True
            elif kw.arg == "static_argnames":
                for c in ast.walk(kw.value):
                    if isinstance(c, ast.Constant) and isinstance(
                            c.value, str):
                        self.static_names.add(c.value)
            elif kw.arg == "static_argnums":
                for c in ast.walk(kw.value):
                    if isinstance(c, ast.Constant) and isinstance(
                            c.value, int):
                        self.static_nums.add(c.value)


def _jit_spec_from_decorators(fn) -> JitSpec:
    """Recognize @jax.jit / @jit / @functools.partial(jax.jit, ...)
    (any import alias of the jax module, e.g. ``_jax.jit``)."""
    spec = JitSpec()
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = _dotted(target)
        if name.endswith("jit") or name == "jit":
            spec.is_jit = True
            if isinstance(dec, ast.Call):
                spec.absorb_call_kwargs(dec)
            continue
        if isinstance(dec, ast.Call) and name.endswith("partial"):
            if dec.args and _dotted(dec.args[0]).endswith("jit"):
                spec.is_jit = True
                spec.absorb_call_kwargs(dec)
    return spec


def _param_names(fn) -> List[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]


class _Taint:
    """Forward taint over one function body: which local names hold
    traced (device) values.  Deliberately simple — two forward sweeps
    handle the straight-line + simple-loop code kernels are written in."""

    def __init__(self, seeds: Set[str]):
        self.tainted = set(seeds)

    # -- expression query ------------------------------------------------

    def expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _UNTAINT_ATTRS:
                return False            # x.shape et al. are static under jit
            return self.expr(node.value)
        if isinstance(node, ast.Subscript):
            return self.expr(node.value)
        if isinstance(node, ast.Call):
            f = _dotted(node.func)
            head = f.split(".")[0]
            if head in ("jnp", "lax"):
                return True             # device-producing call
            if f == "len":
                return False
            if isinstance(node.func, ast.Attribute) and self.expr(
                    node.func.value):
                return True             # method on a traced value
            return any(self.expr(a) for a in node.args) or any(
                self.expr(kw.value) for kw in node.keywords)
        if isinstance(node, (ast.BinOp,)):
            return self.expr(node.left) or self.expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.expr(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return self.expr(node.left) or any(
                self.expr(c) for c in node.comparators)
        if isinstance(node, ast.IfExp):
            return self.expr(node.body) or self.expr(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.expr(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        return False

    # -- statement sweep -------------------------------------------------

    def _mark_target(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._mark_target(e, tainted)
        elif isinstance(target, ast.Starred):
            self._mark_target(target.value, tainted)
        # subscript/attribute stores taint the base conservatively
        elif isinstance(target, ast.Subscript) and tainted:
            self._mark_target(target.value, True)

    def sweep(self, body) -> None:
        for st in body:
            if isinstance(st, ast.Assign):
                t = self.expr(st.value)
                for tgt in st.targets:
                    self._mark_target(tgt, t)
            elif isinstance(st, ast.AugAssign):
                if self.expr(st.value):
                    self._mark_target(st.target, True)
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                self._mark_target(st.target, self.expr(st.value))
            elif isinstance(st, ast.For):
                if self.expr(st.iter):
                    self._mark_target(st.target, True)
                self.sweep(st.body)
                self.sweep(st.orelse)
            elif isinstance(st, (ast.While, ast.If)):
                self.sweep(st.body)
                self.sweep(st.orelse)
            elif isinstance(st, ast.With):
                self.sweep(st.body)
            elif isinstance(st, ast.Try):
                self.sweep(st.body)
                for h in st.handlers:
                    self.sweep(h.body)
                self.sweep(st.orelse)
                self.sweep(st.finalbody)
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested defs (scan bodies, helpers over traced values):
                # their params are traced by construction
                self.tainted.update(_param_names(st))
                self.sweep(st.body)


def _scan_jit_body(src: SourceFile, fn, scope: str, spec: JitSpec,
                   out: List[Finding]) -> None:
    """Flag host syncs + float64 inside one jitted function."""
    params = _param_names(fn)
    seeds = {p for i, p in enumerate(params)
             if p not in spec.static_names and i not in spec.static_nums
             and p != "self"}
    taint = _Taint(seeds)
    # two sweeps: the second catches names that became tainted after
    # their first textual use (simple loops)
    taint.sweep(fn.body)
    taint.sweep(fn.body)

    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = _dotted(node.func)
        # float(x) / int(x) / bool(x) on a traced value
        if f in _SYNC_BUILTINS and node.args and taint.expr(node.args[0]):
            fi = src.finding(
                "jax-host-sync", node, scope,
                f"{f}() on a traced value inside a jitted function — "
                "trace-time concretization (stale constant) or a "
                "device sync per call")
            if fi:
                out.append(fi)
        # x.item() / x.tolist()
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr in _SYNC_METHODS
              and taint.expr(node.func.value)):
            fi = src.finding(
                "jax-host-sync", node, scope,
                f".{node.func.attr}() on a traced value inside a "
                "jitted function — implicit device sync")
            if fi:
                out.append(fi)
        # np.asarray / np.array on a traced value
        elif (f.split(".")[0] in ("np", "numpy")
              and f.split(".")[-1] in ("asarray", "array")
              and node.args and taint.expr(node.args[0])):
            fi = src.finding(
                "jax-host-sync", node, scope,
                f"{f}() on a traced value inside a jitted function — "
                "blocking device->host pull on the hot path")
            if fi:
                out.append(fi)
        # explicit float64
        if ((isinstance(node.func, ast.Attribute)
             and node.func.attr == "astype"
             and node.args
             and _dotted(node.args[0]).endswith("float64"))
                or f.endswith(".float64")):
            fi = src.finding(
                "jax-float64", node, scope,
                "explicit float64 inside a jitted function — silently "
                "float32 under default x64=off, 2x HBM traffic when on")
            if fi:
                out.append(fi)
        for kw in node.keywords:
            if kw.arg == "dtype" and _dotted(kw.value).endswith("float64"):
                fi = src.finding(
                    "jax-float64", kw.value, scope,
                    "dtype=float64 inside a jitted function — silently "
                    "float32 under default x64=off")
                if fi:
                    out.append(fi)


def _module_mutable_globals(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for st in tree.body:
        if isinstance(st, ast.Assign) and isinstance(
                st.value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                           ast.DictComp, ast.SetComp)):
            for tgt in st.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _check_jit_signature(src: SourceFile, fn, scope: str, spec: JitSpec,
                         out: List[Finding]) -> None:
    params = _param_names(fn)
    # ring-buffer args without donation
    rings = [p for p in params
             if p in _RING_NAMES or p.startswith(_RING_PREFIXES)]
    if rings and not spec.donates:
        fi = src.finding(
            "jax-donate-missing", fn, scope,
            f"jitted function takes ring-buffer arg(s) "
            f"{', '.join(rings)} without donate_argnums/donate_argnames "
            "— every step copies the ring instead of aliasing in place")
        if fi:
            out.append(fi)
    # static_argnames whose default is unhashable
    defaults = fn.args.defaults
    pos = fn.args.posonlyargs + fn.args.args
    padded = [None] * (len(pos) - len(defaults)) + list(defaults)
    for p, d in zip(pos, padded):
        if p.arg in spec.static_names and isinstance(
                d, (ast.List, ast.Dict, ast.Set)):
            fi = src.finding(
                "jax-nonhashable-static", d, scope,
                f"static arg {p.arg!r} has an unhashable default — "
                "jit raises at the first defaulted call")
            if fi:
                out.append(fi)
    kw_defaults = dict(zip([a.arg for a in fn.args.kwonlyargs],
                           fn.args.kw_defaults))
    for name, d in kw_defaults.items():
        if name in spec.static_names and isinstance(
                d, (ast.List, ast.Dict, ast.Set)):
            fi = src.finding(
                "jax-nonhashable-static", d, scope,
                f"static arg {name!r} has an unhashable default — "
                "jit raises at the first defaulted call")
            if fi:
                out.append(fi)
    # str/bool-annotated params not marked static
    for i, p in enumerate(pos + fn.args.kwonlyargs):
        ann = getattr(p, "annotation", None)
        if ann is None:
            continue
        tname = _dotted(ann)
        if tname in ("str", "bool") and p.arg not in spec.static_names \
                and i not in spec.static_nums:
            fi = src.finding(
                "jax-unmarked-static", p, scope,
                f"param {p.arg!r} annotated {tname} on a jitted function "
                "but not in static_argnames — strings fail at trace "
                "time, traced bools break Python branching")
            if fi:
                out.append(fi)


def _check_global_capture(src: SourceFile, fn, scope: str,
                          mutable_globals: Set[str],
                          out: List[Finding]) -> None:
    local = set(_param_names(fn))
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        local.add(n.id)
    for node in ast.walk(fn):
        if (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                and node.id in mutable_globals and node.id not in local):
            fi = src.finding(
                "jax-mutable-global-capture", node, scope,
                f"module-level mutable {node.id!r} read inside a jitted "
                "function — the trace snapshots it; later mutations "
                "never invalidate the compiled executable")
            if fi:
                out.append(fi)


def _resolve_local_fn(name: str, module: ast.Module,
                      parent_body) -> Optional[ast.FunctionDef]:
    for body in (parent_body, module.body):
        for st in body:
            if isinstance(st, ast.FunctionDef) and st.name == name:
                return st
    return None


def _iter_jitted_functions(src: SourceFile):
    """Yield (fn, scope, spec) for decorator-style AND call-style jit
    (``step = jax.jit(fn, ...)`` / ``jax.jit(shard_map(inner, ...))``)."""
    module = src.tree
    stack = [(module, "")]
    while stack:
        node, prefix = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                stack.append((child, child.name))
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = f"{prefix}.{child.name}" if prefix else child.name
                if isinstance(child, ast.FunctionDef):
                    spec = _jit_spec_from_decorators(child)
                    if spec.is_jit:
                        yield child, scope, spec
                stack.append((child, scope))
    # call-style: jax.jit(f, ...) where f is a local def (possibly
    # wrapped in shard_map(...))
    for call in ast.walk(module):
        if not isinstance(call, ast.Call):
            continue
        if not _dotted(call.func).endswith("jit"):
            continue
        if not call.args:
            continue
        spec = JitSpec()
        spec.is_jit = True
        spec.absorb_call_kwargs(call)
        inner = call.args[0]
        if isinstance(inner, ast.Call):        # jit(shard_map(f, ...))
            spec.absorb_call_kwargs(inner)
            inner = inner.args[0] if inner.args else None
        if isinstance(inner, ast.Name):
            fn = _resolve_local_fn(inner.id, module, module.body)
            if fn is not None:
                yield fn, fn.name, spec


def _check_hot_roundtrip(src: SourceFile, fn, scope: str,
                         out: List[Finding]) -> None:
    """Eager hot-path rule: np.asarray pull whose result feeds a
    jnp.asarray/device_put re-upload in the same function."""
    pulled: Set[str] = set()

    def value_is_pull(node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                f = _dotted(n.func)
                if (f.split(".")[0] in ("np", "numpy")
                        and f.split(".")[-1] in ("asarray", "array")):
                    return True
            if isinstance(n, ast.Name) and n.id in pulled:
                return True
        return False

    class V(ast.NodeVisitor):
        def visit_Assign(self, node):
            if value_is_pull(node.value):
                for tgt in node.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            pulled.add(n.id)
                        elif isinstance(n, ast.Subscript) and isinstance(
                                n.value, ast.Name):
                            pulled.add(n.value.id)
            self.generic_visit(node)

        def visit_Call(self, node):
            f = _dotted(node.func)
            if (f in ("jnp.asarray", "jnp.array")
                    or f.endswith("device_put")) and node.args:
                arg = node.args[0]
                if any(isinstance(n, ast.Name) and n.id in pulled
                       for n in ast.walk(arg)):
                    fi = src.finding(
                        "jax-host-roundtrip", node, scope,
                        "host value pulled with np.asarray is re-uploaded "
                        "here — a device->host->device round-trip (2 wire "
                        "crossings) for work the device can do in place")
                    if fi:
                        out.append(fi)
            self.generic_visit(node)

    V().visit(fn)


def run(src: SourceFile) -> Iterable[Finding]:
    out: List[Finding] = []
    mutable_globals = _module_mutable_globals(src.tree)
    seen = set()
    for fn, scope, spec in _iter_jitted_functions(src):
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        _scan_jit_body(src, fn, scope, spec, out)
        _check_jit_signature(src, fn, scope, spec, out)
        if mutable_globals:
            _check_global_capture(src, fn, scope, mutable_globals, out)
    # eager-context hot-path round-trips (models orchestration methods)
    stack = [(src.tree, "")]
    while stack:
        node, prefix = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                stack.append((child, child.name))
            elif isinstance(child, ast.FunctionDef):
                scope = f"{prefix}.{child.name}" if prefix else child.name
                if child.name.startswith(HOT_PATH_PREFIXES):
                    _check_hot_roundtrip(src, child, scope, out)
    return out


register_pass("jax-pass", JAX_SCOPE, run)
