"""Static analysis & correctness tooling for the serving path.

The two layers of this system fail *silently*: an accidental
``float(x)`` / ``.item()`` / ``np.asarray`` on a traced value inside a
jitted step costs a hidden device sync (or a retrace) per frame, and a
blocking call or an unlocked cross-thread mutation on the control plane
only ever surfaces as p99 jitter under the fleet bench.  This package
proves the absence of those bug classes mechanically instead of
rediscovering them in BENCH rounds (TurboServe's per-request dispatch
and stall taxes, PAPERS.md — eliminated by construction, checked by CI).

Three pass families, one engine:

- :mod:`.jaxpass` — retrace/host-sync lints over ``ops/``, ``models/``,
  ``parallel/`` (the device program);
- :mod:`.asyncpass` — event-loop blocking + GC'd-task lints over
  ``web/``, ``fleet/``, ``resilience/`` (the control plane);
- :mod:`.ownership` — cross-thread attribute-ownership check driven by
  the annotation registry in that module (the encode-thread <-> event-
  loop boundary PR 6's ``request_degrade_level`` plumbing exists to
  police);
- :mod:`.retrace` — the *runtime* half: a tripwire over the
  ``jax_compile_cache_*`` counters (obs/procstats) that fails a test
  with call-site attribution when the per-frame path recompiles after
  warm-up.

CLI: ``python -m docker_nvidia_glx_desktop_tpu.analysis [--json]`` —
exit 0 when no finding is NEW relative to the committed baseline
(``deploy/analysis_baseline.json``), exit 1 otherwise.  Suppress a
deliberate pattern inline with ``# dngd: ignore[rule-id]``.

Dependency-free by design: stdlib ``ast`` only, so the gate runs in any
environment the repo itself runs in (including the bare CI box before
jax is importable).
"""

from .engine import (AnalysisReport, Finding, load_baseline, run_analysis,
                     write_baseline)

__all__ = ["Finding", "AnalysisReport", "run_analysis", "load_baseline",
           "write_baseline"]
