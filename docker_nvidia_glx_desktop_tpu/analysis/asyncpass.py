"""async-pass: event-loop blocking + GC'd-task lints over the control
plane (``web/``, ``fleet/``, ``resilience/``).

Rules:

- ``async-blocking-call`` — a known-blocking call (``time.sleep``,
  sync subprocess/socket/file I/O, ``requests``/``urlopen``) in the
  body of an ``async def``.  One stalled coroutine stalls EVERY
  session's signaling and media pump on this single-loop server; the
  fix is ``asyncio.sleep``, aiohttp, or ``loop.run_in_executor`` (the
  pattern ``_handle_client_msg`` already uses for xdotool).  The check
  is one level transitive: a call from a coroutine to a *local* sync
  helper that itself blocks is flagged at the call site.
- ``async-task-leak`` — ``asyncio.create_task``/``ensure_future``
  whose result is discarded (a bare expression statement).  The event
  loop holds only a weak reference to scheduled tasks: a GC pass can
  collect the task mid-flight and the work silently never happens
  (asyncio docs, "Important: save a reference").  Assign it, or park it
  in a module-level set with ``add_done_callback(set.discard)``.

Nested *sync* ``def``s inside a coroutine are not scanned as coroutine
code — they are usually executor payloads or marshalled callbacks that
run elsewhere (their call sites are still checked).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from .engine import ASYNC_SCOPE, Finding, SourceFile, register_pass

__all__ = ["run"]

# dotted-call suffixes that block the calling thread
_BLOCKING_CALLS = {
    "time.sleep", "_time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "os.system", "os.popen", "os.waitpid",
    "socket.create_connection", "socket.getaddrinfo",
    "socket.gethostbyname",
    "urllib.request.urlopen", "requests.get", "requests.post",
    "requests.put", "requests.request",
    "io.open",
}
_BLOCKING_BARE = {"open", "Popen", "urlopen"}
# attribute-method names that are file I/O wherever they appear
# (pathlib.Path / importlib.resources traversables)
_BLOCKING_ATTRS = {"read_text", "read_bytes", "write_text", "write_bytes"}

_TASK_SPAWNERS = {"create_task", "ensure_future"}


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _blocking_reason(call: ast.Call) -> Optional[str]:
    f = _dotted(call.func)
    if f in _BLOCKING_CALLS or any(
            f.endswith("." + b) for b in _BLOCKING_CALLS):
        return f
    if f in _BLOCKING_BARE:
        return f
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr in _BLOCKING_ATTRS:
        return f or call.func.attr
    return None


def _iter_own_nodes(body):
    """Walk ``body`` WITHOUT descending into nested function defs at any
    depth (sync defs are executor payloads / marshalled callbacks that
    run off-loop; nested coroutines are visited as their own scope, so
    descending would double-report them)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _direct_blocking_calls(fn) -> List[ast.Call]:
    """Blocking calls lexically inside ``fn``, excluding nested defs
    (see :func:`_iter_own_nodes`)."""
    return [node for node in _iter_own_nodes(fn.body)
            if isinstance(node, ast.Call) and _blocking_reason(node)]


def _local_blocking_helpers(src: SourceFile) -> Set[str]:
    """Names of module-level sync functions (and methods, as
    ``Class.name`` and bare ``name``) that directly block."""
    helpers: Set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.FunctionDef) and _direct_blocking_calls(
                node):
            helpers.add(node.name)
    return helpers


def run(src: SourceFile) -> Iterable[Finding]:
    out: List[Finding] = []
    helpers = _local_blocking_helpers(src)

    # scope annotation for findings
    def scopes():
        stack = [(src.tree, "")]
        while stack:
            node, prefix = stack.pop()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    stack.append((child, child.name))
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    sc = f"{prefix}.{child.name}" if prefix else child.name
                    yield child, sc
                    stack.append((child, sc))

    for fn, scope in scopes():
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        # direct blocking calls on the loop
        for call in _direct_blocking_calls(fn):
            fi = src.finding(
                "async-blocking-call", call, scope,
                f"blocking call {_blocking_reason(call)}() inside "
                "'async def' — stalls every session on this event "
                "loop; use the async equivalent or run_in_executor")
            if fi:
                out.append(fi)
        # one-level transitive: coroutine calls a local sync helper
        # that blocks.  Same nested-def exemption as the direct check:
        # a helper invoked from inside an executor payload runs
        # off-loop, so only on-loop call sites count.
        for node in _iter_own_nodes(fn.body):
            if not isinstance(node, ast.Call):
                continue
            f = _dotted(node.func)
            name = f.split(".")[-1]
            if name in helpers and name != fn.name \
                    and not _blocking_reason(node):
                fi = src.finding(
                    "async-blocking-call", node, scope,
                    f"call to {name}() inside 'async def' — that "
                    "local helper does blocking I/O; hoist the "
                    "read to setup time or run_in_executor")
                if fi:
                    out.append(fi)

    # GC'd tasks: spawner result discarded (anywhere in the module —
    # sync callbacks spawn tasks too, e.g. signal handlers)
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            f = _dotted(node.value.func)
            if f.split(".")[-1] in _TASK_SPAWNERS:
                fi = src.finding(
                    "async-task-leak", node, "<module>",
                    f"{f}(...) result discarded — asyncio keeps only a "
                    "weak ref to scheduled tasks, so GC can cancel this "
                    "work mid-flight; keep a reference "
                    "(add_done_callback(discard) on a module-level set)")
                if fi:
                    out.append(fi)
    return out


register_pass("async-pass", ASYNC_SCOPE, run)
