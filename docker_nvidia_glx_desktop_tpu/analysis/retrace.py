"""Runtime retrace tripwire: fail when the per-frame path recompiles.

The static jax-pass proves the *patterns* that cause retraces are
absent; this module proves the *outcome*: after warm-up, encoding more
frames through the pipelined serving path must trigger ZERO new XLA
compilations.  A retrace on the per-frame path is a silent 20 ms-to-
minutes stall (CPU backend) per occurrence — the exact failure class
BENCH rounds kept rediscovering as p99 outliers.

Mechanism: ``utils/jaxcache`` registers the persistent compile cache,
and every cache-eligible compilation raises a
``/jax/compilation_cache/compile_requests_use_cache`` monitoring event
(the same stream behind the ``jax_compile_cache_{hits,requests,misses}``
counters on ``/metrics``, obs/procstats — PR 2).  The tripwire counts
those events over a ``with`` block and, because the listener runs
synchronously inside the compiling thread, captures the *call stack at
compile time* filtered to repo frames — so a violation names the line
of serving code that caused the recompile, not just "1 compile
happened".

Usage (the pytest fixture in tests/test_analysis.py wraps this)::

    with RetraceTripwire() as tw:
        for f in frames:
            collect(encoder.encode_submit(f))
    tw.assert_quiet()     # raises with call-site attribution

``allowed`` > 0 tolerates a known warm-up set (e.g. the first qp-ladder
step a rate-controlled encoder compiles lazily).
"""

from __future__ import annotations

import threading
import traceback
from typing import List, Optional

__all__ = ["RetraceTripwire", "RetraceError", "compile_events_supported"]

_EVENT = "/jax/compilation_cache/compile_requests_use_cache"

_lock = threading.Lock()
_active: List["RetraceTripwire"] = []
_listener_state = {"registered": False, "ok": None}


class RetraceError(AssertionError):
    """Raised when the guarded block compiled more than allowed."""


def _on_event(event: str, **kwargs) -> None:
    if event != _EVENT or not _active:
        return
    # repo-frame attribution: the listener runs synchronously inside
    # the compiling thread, so the current stack names the caller
    stack = traceback.extract_stack()
    site = None
    for frame in reversed(stack):
        fn = frame.filename.replace("\\", "/")
        if "docker_nvidia_glx_desktop_tpu" in fn and \
                "/analysis/" not in fn:
            site = f"{fn.rsplit('docker_nvidia_glx_desktop_tpu/', 1)[-1]}" \
                   f":{frame.lineno} in {frame.name} ({frame.line})"
            break
    with _lock:
        for tw in _active:
            tw._events.append(site or "<no repo frame on stack>")


def _ensure_listener() -> bool:
    """Register the monitoring listener once per process.  Returns
    False when jax.monitoring is unavailable (tripwire inert)."""
    if _listener_state["registered"]:
        return bool(_listener_state["ok"])
    _listener_state["registered"] = True
    try:
        from jax import monitoring
        monitoring.register_event_listener(_on_event)
        _listener_state["ok"] = True
    except Exception:
        _listener_state["ok"] = False
    return bool(_listener_state["ok"])


def compile_events_supported() -> bool:
    """True when the installed jax emits compile-cache events (the
    tripwire can actually trip)."""
    return _ensure_listener()


class RetraceTripwire:
    """Context manager counting XLA compilations with attribution."""

    def __init__(self, allowed: int = 0,
                 label: Optional[str] = None):
        self.allowed = allowed
        self.label = label or "guarded block"
        self._events: List[str] = []
        self._supported = False

    def __enter__(self) -> "RetraceTripwire":
        self._supported = _ensure_listener()
        with _lock:
            _active.append(self)
        return self

    def __exit__(self, *exc) -> None:
        with _lock:
            if self in _active:
                _active.remove(self)

    @property
    def compiles(self) -> int:
        return len(self._events)

    @property
    def sites(self) -> List[str]:
        return list(self._events)

    def assert_quiet(self) -> None:
        """Raise :class:`RetraceError` when the block compiled more
        than ``allowed`` times, naming each compile's repo call site."""
        if not self._supported:
            return                      # jax without monitoring: inert
        if self.compiles <= self.allowed:
            return
        sites = "\n  ".join(self._events)
        raise RetraceError(
            f"{self.label}: {self.compiles} XLA compilation(s) after "
            f"warm-up (allowed {self.allowed}) — the per-frame path is "
            f"retracing.  Compile call sites:\n  {sites}")
