"""CLI: ``python -m docker_nvidia_glx_desktop_tpu.analysis [--json]``.

Exit codes: 0 = no finding is new relative to the baseline; 1 = new
findings (the CI gate); 2 = bad usage.  ``--write-baseline`` records
the current findings as the accepted set (requires reviewer sign-off in
the PR that commits it).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from .engine import default_baseline_path, run_analysis, write_baseline


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m docker_nvidia_glx_desktop_tpu.analysis",
        description="dependency-free static analysis for the serving "
                    "path (jax retrace/host-sync, asyncio blocking, "
                    "cross-thread ownership)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON on stdout")
    ap.add_argument("--baseline", type=pathlib.Path, default=None,
                    help="baseline path (default: "
                         "deploy/analysis_baseline.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept the current findings as the baseline")
    args = ap.parse_args(argv)

    bp = args.baseline if args.baseline is not None \
        else default_baseline_path()
    report = run_analysis(baseline_path=bp)
    if args.write_baseline:
        write_baseline(report.findings, bp)
        print(f"baseline written: {bp} "
              f"({len(report.findings)} finding(s))")
        return 0
    if args.json:
        print(json.dumps(report.to_dict(), indent=1, sort_keys=True))
    else:
        print(report.render_text())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
