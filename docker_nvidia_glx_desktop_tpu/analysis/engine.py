"""Analysis engine: source model, pragma suppression, findings, baseline.

The engine is deliberately small: passes are plain callables
``(SourceFile) -> Iterable[Finding]`` registered with a scope (which
package directories they run over), findings carry a *stable
fingerprint* (rule + file + enclosing scope + normalized source line —
NOT the line number, so unrelated edits above a finding don't churn the
baseline), and the CI gate is one set difference: a finding whose
fingerprint is absent from ``deploy/analysis_baseline.json`` is NEW and
fails the build.

Inline suppression: ``# dngd: ignore[rule-id]`` (or a comma-separated
list, or ``*``) on the offending line, or on a comment line immediately
above it, silences the finding at that site.  Use it for deliberate
patterns with a justification in the surrounding comment — the pragma
is greppable, the baseline is not.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import pathlib
import re
import tokenize
from typing import Callable, Dict, Iterable, List, Optional, Sequence

__all__ = ["Finding", "SourceFile", "AnalysisReport", "PASSES",
           "register_pass", "run_analysis", "load_baseline",
           "write_baseline", "iter_source_files"]

_PRAGMA_RE = re.compile(
    r"#\s*dngd:\s*ignore\[([A-Za-z0-9_\-*,\s]+)\]")

# Default scan scope per pass family, relative to the package root.
# The jax passes cover the device program; the async + ownership passes
# cover the concurrent control plane.
JAX_SCOPE = ("ops", "models", "parallel")
ASYNC_SCOPE = ("web", "fleet", "resilience")


@dataclasses.dataclass
class Finding:
    rule: str                 # kebab-case rule id (pragma target)
    path: str                 # repo-relative posix path
    line: int                 # 1-based line (advisory; not fingerprinted)
    col: int
    scope: str                # enclosing qualname ("Class.method" / "<module>")
    message: str
    snippet: str = ""         # the offending source line, stripped

    @property
    def fingerprint(self) -> str:
        """Stable identity: survives line drift from unrelated edits.
        Two identical offending lines in the same scope collide on
        purpose (fixing one fixes the pattern; the gate re-flags the
        survivor on its next edit)."""
        key = "\x1f".join((self.rule, self.path, self.scope,
                           " ".join(self.snippet.split())))
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {"fingerprint": self.fingerprint, "rule": self.rule,
                "path": self.path, "line": self.line, "scope": self.scope,
                "message": self.message, "snippet": self.snippet}

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.scope}] {self.message}")


class SourceFile:
    """One parsed module: AST + per-line pragma suppressions."""

    def __init__(self, path: pathlib.Path, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel)
        self.pragmas = self._collect_pragmas(text)

    @staticmethod
    def _collect_pragmas(text: str) -> Dict[int, set]:
        """line -> set of suppressed rule ids ("*" = all).  A pragma on
        a comment-only line also covers the next non-blank line, so long
        expressions can carry their justification above them."""
        out: Dict[int, set] = {}
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(text).readline))
        except (tokenize.TokenError, IndentationError):
            return out
        lines = text.splitlines()
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            row = tok.start[0]
            out.setdefault(row, set()).update(rules)
            # comment-only line: extend to the next non-blank line
            if lines[row - 1].lstrip().startswith("#"):
                nxt = row + 1
                while nxt <= len(lines) and not lines[nxt - 1].strip():
                    nxt += 1
                out.setdefault(nxt, set()).update(rules)
        return out

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self.pragmas.get(line)
        return bool(rules) and ("*" in rules or rule in rules)

    def finding(self, rule: str, node: ast.AST, scope: str,
                message: str) -> Optional[Finding]:
        """Build a Finding for ``node`` unless a pragma suppresses it."""
        line = getattr(node, "lineno", 1)
        if self.suppressed(rule, line):
            return None
        snippet = (self.lines[line - 1].strip()
                   if 0 < line <= len(self.lines) else "")
        return Finding(rule=rule, path=self.rel, line=line,
                       col=getattr(node, "col_offset", 0) + 1,
                       scope=scope, message=message, snippet=snippet)


# -- pass registry --------------------------------------------------------

# name -> (scope dirs, callable(SourceFile) -> Iterable[Finding])
PASSES: Dict[str, tuple] = {}


def register_pass(name: str, scope: Sequence[str],
                  fn: Callable[[SourceFile], Iterable[Finding]]) -> None:
    PASSES[name] = (tuple(scope), fn)


def _ensure_passes_loaded() -> None:
    # import side effect registers each pass family exactly once
    from . import asyncpass, jaxpass, ownership  # noqa: F401


def package_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parent.parent


def repo_root() -> pathlib.Path:
    return package_root().parent


def iter_source_files(scope: Sequence[str],
                      root: Optional[pathlib.Path] = None):
    """Yield SourceFile for every .py under the scope dirs (package-
    relative), sorted for deterministic reports."""
    pkg = root if root is not None else package_root()
    base = pkg.parent
    for sub in scope:
        d = pkg / sub
        if not d.is_dir():
            continue
        for p in sorted(d.rglob("*.py")):
            if "__pycache__" in p.parts:
                continue
            rel = p.relative_to(base).as_posix()
            # a file the analyzer cannot parse must fail the gate loudly,
            # not be skipped (SyntaxError propagates)
            yield SourceFile(p, rel, p.read_text())


@dataclasses.dataclass
class AnalysisReport:
    findings: List[Finding]
    new: List[Finding]            # not in baseline
    fixed: List[dict]             # baseline entries no longer found
    baseline_path: str

    @property
    def ok(self) -> bool:
        # stale baseline entries fail the gate too: the baseline must
        # stay the honest, minimal accepted set (regenerate on fix)
        return not self.new and not self.fixed

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "baseline": self.baseline_path,
            "counts": {"total": len(self.findings), "new": len(self.new),
                       "fixed": len(self.fixed)},
            "findings": [f.to_dict() for f in self.findings],
            "new_findings": [f.to_dict() for f in self.new],
            "fixed_findings": self.fixed,
        }

    def render_text(self) -> str:
        out = []
        for f in self.findings:
            mark = "NEW " if f in self.new else "     "
            out.append(mark + f.render())
        out.append("")
        out.append(f"{len(self.findings)} finding(s): "
                   f"{len(self.new)} new, {len(self.fixed)} fixed "
                   f"vs baseline ({self.baseline_path})")
        if self.new:
            out.append("new findings fail the gate — fix them, suppress "
                       "with '# dngd: ignore[rule-id]' + justification, "
                       "or regenerate the baseline (--write-baseline) "
                       "with reviewer sign-off")
        if self.fixed:
            out.append("fixed findings: regenerate the baseline "
                       "(--write-baseline) to keep it honest")
        return "\n".join(out)


def run_passes(root: Optional[pathlib.Path] = None) -> List[Finding]:
    _ensure_passes_loaded()
    findings: List[Finding] = []
    # parse each file ONCE and dispatch it to every pass whose scope
    # covers its subpackage (async-pass and ownership-pass share the
    # whole control-plane tree; re-parsing it per pass doubled the
    # gate's wall time)
    passes = sorted(PASSES.items())
    union = sorted({d for _, (scope, _) in passes for d in scope})
    for src in iter_source_files(union, root=root):
        parts = src.rel.split("/")
        sub = parts[1] if len(parts) > 1 else ""
        for name, (scope, fn) in passes:
            if sub in scope:
                findings.extend(fn(src))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# -- baseline -------------------------------------------------------------

def default_baseline_path() -> pathlib.Path:
    return repo_root() / "deploy" / "analysis_baseline.json"


def load_baseline(path: Optional[pathlib.Path] = None) -> dict:
    p = path if path is not None else default_baseline_path()
    if not p.exists():
        return {"version": 1, "findings": []}
    return json.loads(p.read_text())


def baseline_doc(findings: Sequence[Finding]) -> dict:
    """Serialize findings as a baseline document.  Sorted + keyed by
    fingerprint so load -> re-emit is byte-identical (tested)."""
    entries = sorted((f.to_dict() for f in findings),
                     key=lambda d: (d["path"], d["rule"], d["fingerprint"]))
    return {"version": 1, "findings": entries}


def write_baseline(findings: Sequence[Finding],
                   path: Optional[pathlib.Path] = None) -> pathlib.Path:
    p = path if path is not None else default_baseline_path()
    p.write_text(json.dumps(baseline_doc(findings), indent=1,
                            sort_keys=True) + "\n")
    return p


def run_analysis(root: Optional[pathlib.Path] = None,
                 baseline_path: Optional[pathlib.Path] = None
                 ) -> AnalysisReport:
    """Run every registered pass and diff against the baseline."""
    findings = run_passes(root=root)
    bp = baseline_path if baseline_path is not None \
        else default_baseline_path()
    base = load_baseline(bp)
    known = {e["fingerprint"] for e in base.get("findings", [])}
    current = {f.fingerprint for f in findings}
    new = [f for f in findings if f.fingerprint not in known]
    fixed = [e for e in base.get("findings", [])
             if e["fingerprint"] not in current]
    return AnalysisReport(findings=findings, new=new, fixed=fixed,
                          baseline_path=str(bp))
